"""Serving front-end: request streams multiplexed over replica groups.

A **replica** is one model copy behind one `DecodeEngine` + `Scheduler`
pair. Two backends share one driver surface:

  * ``backend="inline"`` — engines in this process, ticked round-robin
    (deterministic; what unit tests and single-host serving use);
  * ``backend="process"`` — each replica is a `runtime.WorkerGroup` of
    one worker process with its own jax runtime, streaming tokens back
    over the group's side channel. Replica death is classified by the
    resilience taxonomy (`resilience.policy.classify_failure`) and,
    within the restart budget, the driver **respawns** the replica: the
    worker reloads weights from the params file, re-warms the step
    through the persistent compile cache (`pipeline.compile_cache` —
    the restart deserializes instead of recompiling), announces itself
    live, and REPLAYS the requests the dead replica had not finished.
    Replay is bitwise-safe by construction — per-request seeds make a
    decoded stream a pure function of the request — so a kill corrupts
    nothing: surviving replicas never notice, and the replayed streams
    are identical to what the dead replica would have produced
    (test-pinned; the serve --smoke gate injects a real SIGKILL).

Telemetry: each replica owns a `telemetry.TelemetryRecorder` and
records the serving span vocabulary (queue_wait / prefill / decode /
detokenize, spans.SERVE_PHASES) per COMPLETED request — cadence-safe —
plus per-request TTFT/TPOT meta the `report` CLI aggregates into its
serving section (docs/OBSERVABILITY.md). PREEMPTED requests get
REPLAYED-tagged spans for their discarded prefix, and whatever is
still in flight at drain time gets INFLIGHT-tagged spans, so a
preempt-heavy or killed run stops under-reporting queue_wait (the tags
keep the report from double-counting the replayed prefix).

Live metrics (telemetry/metrics.py): each replica additionally owns a
`MetricsRegistry` (per-tick queue/slot/pool gauges, event counters,
mergeable latency histograms, flushed to uid-tagged JSONL on the tick
cadence) and a `FlightRecorder` (bounded ring of recent ticks +
scheduler events, cadence-persisted). The driver merges the per-replica
streams into run-level histograms in ``serving.json`` (quantiles from
BUCKETS, exact across replicas and respawned attempts), finalizes a
dead replica's flight ring into ``<run_dir>/flight.json`` stamped with
the resilience classification, and exposes `load_signal(run_dir)` —
the queue-depth/occupancy oracle input ROADMAP item 1(c) autoscale
consumes.

Dynamic serving session (docs/AUTOSCALE.md): beyond the fixed-batch
``run()``, `start()` opens a LIVE session with the autoscale actuation
seams — ``submit()`` (routes to live replicas; defers with a
structured reason when every replica is draining/dead), ``tick()``,
``add_replica()`` (exactly the respawn path: npz reload + persistent
compile-cache re-warm), ``remove_replica(graceful=True)`` (stop
admissions, drain slots to retirement, requeue queued work onto
survivors via the bitwise replay seam), ``stop()``. The
`autoscale.AutoscaleController` drives these from the load signal.
"""
from __future__ import annotations

import dataclasses
import json
import os
import signal
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ray_lightning_tpu.serve.engine import DecodeEngine, EngineConfig
from ray_lightning_tpu.serve.scheduler import (
    Completion, Request, Scheduler, SLOConfig,
)
from ray_lightning_tpu.analysis.lockwatch import san_lock
from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)

#: spans are flushed every this many completions (and at shutdown) —
#: the serving analog of the trainer's logging cadence
FLUSH_EVERY_N_COMPLETIONS = 16


# ---- params serialization (the replica weight-reload path) ----------------

def save_params_npz(params, path: str) -> None:
    """Flatten a params pytree to one .npz keyed by `/`-joined paths —
    the weight file a (re)spawned replica loads. Exact round-trip:
    numpy arrays at their stored dtypes, no re-quantization."""
    from ray_lightning_tpu.utils.pytree import named_leaves

    flat = {path_: np.asarray(leaf) for path_, leaf in
            named_leaves(params)}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    np.savez(path, **flat)


def load_params_npz(path: str):
    """Rebuild the nested params dict from `save_params_npz` output."""
    out: Dict[str, Any] = {}
    with np.load(path) as data:
        for key in data.files:
            node = out
            parts = key.split("/")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = data[key]
    return out


# ---- configuration --------------------------------------------------------

@dataclasses.dataclass
class ReplicaGroupConfig:
    """How the driver runs its replicas."""

    n_replicas: int = 1
    backend: str = "inline"              # "inline" | "process"
    engine: EngineConfig = dataclasses.field(default_factory=EngineConfig)
    reserve: str = "worst_case"
    #: run dir: telemetry spans + serving.json summary land here
    run_dir: Optional[str] = None
    #: persistent compile cache (pipeline.compile_cache) — respawned
    #: replicas deserialize the step instead of recompiling
    compile_cache_dir: Optional[str] = None
    max_restarts: int = 2
    #: extra env for process replicas (e.g. {"JAX_PLATFORMS": "cpu"})
    env: Optional[Dict[str, str]] = None
    start_timeout: float = 180.0
    #: tensor-parallel degree of each replica (docs/SERVING.md "sharded
    #: replicas"): tp > 1 makes every PROCESS replica a
    #: `runtime.WorkerGroup` of tp ranks over its own tensor mesh —
    #: the engine's one step lowers as an SPMD program, the pool
    #: shards over KV heads, every rank runs the scheduler in lockstep
    #: off the request channel, and rank 0 owns the replica's result
    #: stream + telemetry. Dynamic sessions only (start/submit/stop).
    tp: int = 1
    #: jax platform for session replica ranks (None = inherit the
    #: worker env; CI sets "cpu" for the gloo fabric)
    platform: Optional[str] = None
    #: CPU devices per rank — with ``platform="cpu"`` this is the
    #: dev-box/CI stand-in for per-host TPU chips (runtime.launch)
    cpu_devices_per_rank: Optional[int] = None
    #: live metrics + flight recorder (telemetry/metrics.py) — armed
    #: only when ``run_dir`` is set; False turns both off even then
    #: (the zero-overhead pin covers the off state)
    metrics: bool = True
    #: metrics JSONL flush cadence in engine ticks (RLT501: never 1-ish
    #: small on a hot production loop; the smoke uses small values so
    #: short runs still land samples)
    metrics_flush_every_n_ticks: int = 32
    #: flight-recorder ring length (recent ticks + scheduler events)
    flight_ring: int = 256
    #: flight ring persist cadence in recorded events
    flight_persist_every: int = 16
    #: draft model config (models.llama.LlamaConfig) for speculative
    #: decoding — arms together with ``engine.draft``; inline replicas
    #: only (the process respawn path reloads ONE params .npz and the
    #: wire carries no draft weights)
    draft_model_cfg: Optional[Any] = None
    #: traffic classes + graceful-overload policy
    #: (scheduler.SLOConfig, docs/SERVING.md "traffic & SLO classes").
    #: None keeps the historical single-class scheduler byte-identical
    slo: Optional[SLOConfig] = None

    def __post_init__(self):
        if self.backend not in ("inline", "process"):
            raise ValueError(f"backend={self.backend!r}")
        if self.backend == "process" and (
                self.engine.draft is not None
                or self.draft_model_cfg is not None):
            raise ValueError(
                "speculative decoding is inline-only: a process "
                "replica (re)spawns from the params .npz, which "
                "carries no draft weights")
        if (self.engine.draft is None) != (self.draft_model_cfg is None):
            raise ValueError(
                "engine.draft and draft_model_cfg arm together — set "
                "both (speculative) or neither")
        if self.n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        if self.tp < 1:
            raise ValueError("tp must be >= 1")
        if self.tp > 1 and self.backend != "process":
            raise ValueError(
                "tp > 1 needs backend='process': a sharded replica is "
                "a WorkerGroup of tp rank processes over its own mesh")


@dataclasses.dataclass
class ServeResult:
    #: rid -> emitted token ids
    outputs: Dict[str, List[int]]
    #: rid -> completion metadata (ttft_s, tpot_s, queue_wait_s, ...)
    meta: Dict[str, dict]
    #: replica_id -> restarts performed
    restarts: Dict[int, int]
    #: aggregate serving stats (decode_tokens_per_s, slot_occupancy, ...)
    stats: dict


# ---- per-request telemetry -------------------------------------------------

def _record_completion(recorder, comp: Completion, replica: int) -> None:
    """Emit the request's serving spans from the scheduler's measured
    host times. Explicit `record()` calls with back-dated starts: the
    spans were already over when the request completed."""
    from ray_lightning_tpu.telemetry.spans import (
        PH_DECODE, PH_PREFILL, PH_QUEUE_WAIT,
    )

    decode_start = time.perf_counter() - comp.decode_s   # first token
    prefill_start = decode_start - comp.ttft_s           # admission
    meta = {"rid": comp.rid, "replica": replica,
            "tokens": len(comp.tokens), "ttft_s": round(comp.ttft_s, 6),
            "tpot_s": round(comp.tpot_s, 6),
            "finish": comp.finish_reason, "preempted": comp.preempted}
    recorder.record(PH_QUEUE_WAIT, prefill_start - comp.queue_wait_s,
                    comp.queue_wait_s, meta={"rid": comp.rid})
    recorder.record(PH_PREFILL, prefill_start, comp.ttft_s,
                    meta={"rid": comp.rid})
    recorder.record(PH_DECODE, decode_start, comp.decode_s, meta=meta)


def _record_partial_spans(recorder, info: dict, meta: dict) -> None:
    """Back-dated queue_wait / prefill / decode spans for a request's
    PARTIAL progress (`Scheduler._partial_timing` shape). The one place
    span back-dating happens for non-completed requests — preemption
    and drain accounting can never drift apart. ``meta`` must carry the
    distinguishing tag (``replayed`` / ``inflight``) and must NOT carry
    ``ttft_s``: its absence is what keeps the report's per-request
    aggregation from double-counting these."""
    from ray_lightning_tpu.telemetry.spans import (
        PH_DECODE, PH_PREFILL, PH_QUEUE_WAIT,
    )

    now = time.perf_counter()
    decode_start = now - info["decode_s"]
    prefill_start = decode_start - info["prefill_s"]
    recorder.record(PH_QUEUE_WAIT,
                    prefill_start - info["queue_wait_s"],
                    info["queue_wait_s"], meta=meta)
    if info["prefill_s"] > 0:
        recorder.record(PH_PREFILL, prefill_start, info["prefill_s"],
                        meta=meta)
    if info["decode_s"] > 0:
        recorder.record(PH_DECODE, decode_start, info["decode_s"],
                        meta=meta)


def _record_preemption(recorder, detail: dict, replica: int) -> None:
    """Spans for the DISCARDED prefix of a just-preempted request,
    tagged ``replayed`` — the report shows the wall this prefix burned
    without double-counting it into the request's final latency (the
    retirement spans cover the replayed run)."""
    _record_partial_spans(recorder, detail, {
        "rid": detail["rid"], "replica": replica, "replayed": True,
        "emitted": detail["emitted"], "preempted": detail["preempted"]})


def _record_drain(recorder, sched, replica: int) -> None:
    """Spans for requests STILL IN FLIGHT when serving stops (replica
    death, shutdown): tagged ``inflight`` so their partial queue_wait /
    prefill / decode wall is accounted instead of vanishing with the
    slot state. Tags keep the report from treating them as completed
    requests."""
    for info in sched.inflight_snapshot():
        _record_partial_spans(recorder, info, {
            "rid": info["rid"], "replica": replica, "inflight": True,
            "state": info["state"], "emitted": info["emitted"],
            "preempted": info["preempted"]})


def _make_recorder(run_dir: Optional[str], replica: int):
    from ray_lightning_tpu.telemetry.spans import (
        NULL_RECORDER, TelemetryRecorder,
    )

    if run_dir is None:
        return NULL_RECORDER
    return TelemetryRecorder(
        os.path.join(run_dir, "telemetry"), rank=replica)


def _make_metrics(run_dir: Optional[str], replica: int,
                  enabled: bool = True, flush_every: int = 32):
    from ray_lightning_tpu.telemetry.metrics import (
        NULL_METRICS, MetricsRegistry,
    )

    if run_dir is None or not enabled:
        return NULL_METRICS
    return MetricsRegistry(os.path.join(run_dir, "telemetry"),
                           replica=replica,
                           flush_every_n_ticks=flush_every)


def _make_flight(run_dir: Optional[str], replica: int,
                 enabled: bool = True, maxlen: int = 256,
                 persist_every: int = 16):
    from ray_lightning_tpu.telemetry.metrics import (
        NULL_FLIGHT, FlightRecorder, flight_path,
    )

    if run_dir is None or not enabled:
        return NULL_FLIGHT
    return FlightRecorder(
        flight_path(os.path.join(run_dir, "telemetry"), replica),
        replica=replica, maxlen=maxlen, persist_every=persist_every)


# ---- one replica's serving loop (runs in-process or in the worker) --------

def _serve_loop(engine: DecodeEngine, reserve: str,
                requests: Sequence[Request], replica: int,
                run_dir: Optional[str] = None,
                on_token=None, on_completion=None, on_preempt=None,
                fault: Optional[dict] = None,
                fault_dir: Optional[str] = None,
                metrics_cfg: Optional[dict] = None,
                slo: Optional[SLOConfig] = None, on_shed=None):
    """Drain ``requests`` through one replica. ``on_token(rid, tok)``
    streams tokens as they are emitted; ``on_completion(comp)`` fires at
    retirement. ``fault={"kill_after_tokens": n}`` SIGKILLs this process
    after the n-th emitted token, once per ``fault_dir`` marker — the
    smoke gate's mid-stream replica death. ``metrics_cfg`` carries the
    `ReplicaGroupConfig` metrics knobs (enabled / flush cadence / flight
    ring)."""
    mc = metrics_cfg or {}
    recorder = _make_recorder(run_dir, replica)
    metrics = _make_metrics(run_dir, replica,
                            enabled=mc.get("enabled", True),
                            flush_every=mc.get("flush_every", 32))
    flight = _make_flight(run_dir, replica,
                          enabled=mc.get("enabled", True),
                          maxlen=mc.get("flight_ring", 256),
                          persist_every=mc.get("flight_persist_every",
                                               16))
    engine.metrics = metrics
    sched = Scheduler(engine, reserve=reserve, metrics=metrics,
                      flight=flight, slo=slo)

    def drain_sheds():
        # typed shed records are terminal statuses, never silence
        # (RLT505): every record reaches the caller's stream
        for rec in sched.take_sheds():
            if on_shed is not None:
                on_shed(rec)

    for req in requests:
        sched.submit(req)
    drain_sheds()  # enqueue-time budget sheds fire before any tick
    emitted_total = 0
    kill_after = int((fault or {}).get("kill_after_tokens", 0))
    marker = (os.path.join(fault_dir, f"replica{replica}.killed")
              if fault_dir else None)
    done: List[Completion] = []
    while sched.busy():
        completions = sched.tick()
        for detail in sched.last_preemption_details:
            # account the discarded prefix (replayed-tagged) — the
            # replay regenerates the stream bitwise, so a consumer
            # keeping the prefix would duplicate tokens
            _record_preemption(recorder, detail, replica)
            if on_preempt is not None:
                on_preempt(detail["rid"])
        for rid, tok in sched.last_emissions:
            emitted_total += 1
            if on_token is not None:
                on_token(rid, tok)
        for comp in completions:
            done.append(comp)
            _record_completion(recorder, comp, replica)
            if on_completion is not None:
                on_completion(comp)
            if len(done) % FLUSH_EVERY_N_COMPLETIONS == 0:
                recorder.flush()
        drain_sheds()
        if (kill_after and emitted_total >= kill_after and marker
                and not os.path.exists(marker)):
            # fire-once across respawns: the marker outlives this
            # process, so the replayed replica serves to completion.
            # Drain-time accounting + a final metrics/flight flush land
            # BEFORE the kill — the injected drill leaves an exact
            # final-ticks postmortem (a real SIGKILL leaves the last
            # cadence-persisted ring, at most one cadence stale).
            with open(marker, "w") as f:
                f.write(str(emitted_total))
            _record_drain(recorder, sched, replica)
            recorder.flush()
            metrics.flush()
            flight.persist()
            os.kill(os.getpid(), signal.SIGKILL)
    _record_drain(recorder, sched, replica)
    recorder.flush()
    recorder.close()
    metrics.close()
    flight.close()
    return done, sched


# ---- process-replica worker main ------------------------------------------

def _replica_worker_main(model_cfg_kw: dict, params_path: str,
                         engine_kw: dict, reserve: str,
                         request_dicts: List[dict], replica: int,
                         run_dir: Optional[str],
                         compile_cache_dir: Optional[str],
                         fault: Optional[dict],
                         fault_dir: Optional[str],
                         metrics_cfg: Optional[dict] = None,
                         slo_kw: Optional[dict] = None) -> dict:
    """Runs inside the WorkerGroup worker process: rebuild the model,
    reload weights, warm the step (persistent compile cache when
    armed), announce live, then serve — streaming every token over the
    side channel so the driver holds partial streams when this process
    dies mid-request."""
    import jax.numpy as jnp

    from ray_lightning_tpu.models.llama import Llama, LlamaConfig
    from ray_lightning_tpu.runtime import session

    if compile_cache_dir:
        from ray_lightning_tpu.pipeline.compile_cache import (
            enable_persistent_cache,
        )

        enable_persistent_cache(compile_cache_dir)
    dtype = model_cfg_kw.pop("dtype", "float32")
    cfg = LlamaConfig(**model_cfg_kw, dtype=jnp.dtype(dtype))
    model = Llama(cfg)
    params = load_params_npz(params_path)
    t0 = time.perf_counter()
    engine = DecodeEngine(model, params, EngineConfig(**engine_kw))
    engine.warmup()
    warm_s = time.perf_counter() - t0
    session.put_queue(("live", replica, {"warmup_s": round(warm_s, 3)}))
    requests = [Request(**d) for d in request_dicts]

    def on_token(rid, tok):
        session.put_queue(("tok", replica, rid, tok))

    def on_preempt(rid):
        session.put_queue(("preempt", replica, rid))

    def on_completion(comp):
        session.put_queue(("done", replica, comp.rid, {
            "finish_reason": comp.finish_reason,
            "queue_wait_s": comp.queue_wait_s,
            "ttft_s": comp.ttft_s, "tpot_s": comp.tpot_s,
            "decode_s": comp.decode_s, "preempted": comp.preempted,
            "n_tokens": len(comp.tokens),
            "priority": comp.priority,
        }))

    def on_shed(rec):
        session.put_queue(("shed", replica, rec["rid"], rec))

    done, sched = _serve_loop(engine, reserve, requests, replica,
                              run_dir=run_dir, on_token=on_token,
                              on_completion=on_completion,
                              on_preempt=on_preempt, fault=fault,
                              fault_dir=fault_dir,
                              metrics_cfg=metrics_cfg,
                              slo=SLOConfig.from_wire(slo_kw),
                              on_shed=on_shed)
    return {"replica": replica, "completed": len(done),
            "steps": engine.steps, "warmup_s": warm_s,
            "compile_count": engine.compile_count,
            "occupancy": sched.slot_occupancy}


# ---- dynamic-session replica worker (the request-channel consumer) --------

def _replica_session_main(model_cfg_kw: dict, params_path: str,
                          engine_kw: dict, reserve: str, replica: int,
                          run_dir: Optional[str], session_dir: str,
                          compile_cache_dir: Optional[str],
                          fault: Optional[dict],
                          fault_dir: Optional[str],
                          metrics_cfg: Optional[dict],
                          channel_epoch: int, tp: int,
                          slo_kw: Optional[dict] = None,
                          rank: int = 0) -> dict:
    """One rank of a DYNAMIC-SESSION replica group (serve/channel.py).

    Unlike `_replica_worker_main` (fixed batch shipped at spawn), work
    arrives over the per-replica command log and results stream back
    over the existing side channel — the bidirectional wire that lets
    `ServeDriver` sessions scale a process deployment.

    Every rank (``tp > 1``: the replica spans a WorkerGroup over its
    own tensor mesh) holds the FULL host-side scheduler in lockstep;
    rank 0 is the replica **leader**: it alone reads commands at its
    own pace, journals each state-changing iteration to the cursor log,
    emits results/acks, and owns the replica's telemetry streams
    (leader-aggregated: one metrics/flight/span stream per replica, not
    per rank). Followers replay the leader's journal — scheduler
    determinism makes their state bit-identical — so the SPMD step
    always sees every rank enter the same tick with the same inputs.

    Results are BATCHED one side-channel item per tick (tokens,
    preemptions, completions, the command ack, evictions together) —
    the channel's documented discipline, lint-enforced as RLT504."""
    import jax.numpy as jnp

    from ray_lightning_tpu.models.llama import Llama, LlamaConfig
    from ray_lightning_tpu.runtime import session
    from ray_lightning_tpu.serve.channel import (
        ChannelReader, CursorReader, CursorWriter, request_from_wire,
        request_to_wire,
    )

    if compile_cache_dir:
        from ray_lightning_tpu.pipeline.compile_cache import (
            enable_persistent_cache,
        )

        enable_persistent_cache(compile_cache_dir)
    dtype = model_cfg_kw.pop("dtype", "float32")
    cfg = LlamaConfig(**model_cfg_kw, dtype=jnp.dtype(dtype))
    model = Llama(cfg)
    params = load_params_npz(params_path)
    mesh = None
    if tp > 1:
        from ray_lightning_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(tensor=tp)
    t0 = time.perf_counter()
    engine = DecodeEngine(model, params, EngineConfig(**engine_kw),
                          mesh=mesh)
    engine.warmup()
    warm_s = time.perf_counter() - t0
    leader = rank == 0
    mc = metrics_cfg or {}
    tdir = run_dir if leader else None
    recorder = _make_recorder(tdir, replica)
    metrics = _make_metrics(tdir, replica, enabled=mc.get("enabled", True),
                            flush_every=mc.get("flush_every", 32))
    flight = _make_flight(tdir, replica, enabled=mc.get("enabled", True),
                          maxlen=mc.get("flight_ring", 256),
                          persist_every=mc.get("flight_persist_every", 16))
    engine.metrics = metrics
    sched = Scheduler(engine, reserve=reserve, metrics=metrics,
                      flight=flight, slo=SLOConfig.from_wire(slo_kw))
    reader = ChannelReader(session_dir, replica, channel_epoch)
    cursor_w = (CursorWriter(session_dir, replica, channel_epoch)
                if leader and tp > 1 else None)
    cursor_r = (CursorReader(session_dir, replica, channel_epoch)
                if not leader else None)
    if leader:
        session.put_queue(("live", replica,
                           {"warmup_s": round(warm_s, 3)}))
    kill_after = int((fault or {}).get("kill_after_tokens", 0))
    marker = (os.path.join(fault_dir, f"replica{replica}.killed")
              if fault_dir else None)
    emitted_total = 0
    state = {"draining": False, "paused": False, "stop": None}

    def apply(cmd) -> List:
        """Apply one command to the local scheduler; returns evictions
        (same on every rank — only the leader WIRES them back)."""
        op = cmd["op"]
        ev: List = []
        if op == "submit":
            sched.enqueue(request_from_wire(cmd["req"]),
                          int(cmd.get("preempts", 0)))
        elif op == "drain":
            state["draining"] = True
            sched.begin_drain()
            ev = sched.evict_queued()
        elif op == "stop":
            mode = cmd.get("mode", "finish")
            state["stop"] = mode
            if mode == "hard":
                sched.begin_drain()
                ev = sched.evict_queued() + sched.evict_slotted()
        elif op == "pause":
            state["paused"] = True
        elif op == "resume":
            state["paused"] = False
        return ev

    def run_tick():
        """One scheduler tick -> the batched result item's fields."""
        completions = sched.tick()
        toks = [[rid, int(tok)] for rid, tok in sched.last_emissions]
        preempts = list(sched.last_preemptions)
        for detail in sched.last_preemption_details:
            _record_preemption(recorder, detail, replica)
        dones = []
        for comp in completions:
            _record_completion(recorder, comp, replica)
            dones.append([comp.rid, {
                "finish_reason": comp.finish_reason,
                "queue_wait_s": comp.queue_wait_s,
                "ttft_s": comp.ttft_s, "tpot_s": comp.tpot_s,
                "decode_s": comp.decode_s, "preempted": comp.preempted,
                "n_tokens": len(comp.tokens),
                "priority": comp.priority,
            }])
            if len(sched.completions) % FLUSH_EVERY_N_COMPLETIONS == 0:
                recorder.flush()
        # mid-drain growth-stall preemptions land back in the closed
        # queue — evict them for the survivors, like the inline tick
        ev = sched.evict_queued() if state["draining"] else []
        return toks, preempts, dones, ev

    if leader:
        while True:
            cmds = reader.poll()
            evicted: List = []
            starts: List = []
            for cmd in cmds:
                if cmd["op"] == "submit":
                    # announce every accepted submit: the driver resets
                    # the stream's output prefix on this — a no-op for
                    # fresh work, THE stale-prefix drop for an epoch
                    # replay after respawn
                    starts.append(cmd["req"]["rid"])
                evicted.extend(apply(cmd))
            # enqueue-time budget sheds (typed records, RLT505) fire
            # inside apply(); tick-time dry-pool sheds extend below
            sheds = sched.take_sheds()
            if state["stop"] in ("hard", "abort"):
                if cursor_w is not None and cmds:
                    cursor_w.advance(reader.last_seq, False)
                payload = {"ack": reader.last_seq}
                if evicted:
                    payload["evicted"] = [[request_to_wire(q), p]
                                          for q, p in evicted]
                if sheds:
                    payload["sheds"] = sheds
                if cmds or evicted or sheds:
                    session.put_queue(("batch", replica, payload))
                break
            do_tick = not state["paused"] and sched.busy()
            if cursor_w is not None and (cmds or do_tick):
                # journal BEFORE the tick: the step's collectives block
                # until the followers join, and they join by reading
                # this record
                cursor_w.advance(reader.last_seq, do_tick)
            toks, preempts, dones, ev2 = (run_tick() if do_tick
                                          else ([], [], [], []))
            evicted.extend(ev2)
            sheds.extend(sched.take_sheds())
            emitted_total += len(toks)
            if cmds or toks or preempts or dones or evicted or sheds:
                # ONE side-channel item per iteration — tokens, acks,
                # completions, evictions, sheds batched (RLT504)
                payload: Dict[str, Any] = {}
                if starts:
                    payload["starts"] = starts
                if toks:
                    payload["toks"] = toks
                if preempts:
                    payload["preempts"] = preempts
                if dones:
                    payload["dones"] = dones
                if evicted:
                    payload["evicted"] = [[request_to_wire(q), p]
                                          for q, p in evicted]
                if sheds:
                    payload["sheds"] = sheds
                if cmds:
                    payload["ack"] = reader.last_seq
                session.put_queue(("batch", replica, payload))
            if (kill_after and emitted_total >= kill_after and marker
                    and not os.path.exists(marker)):
                # fire-once mid-stream SIGKILL (the ramp leg's injected
                # death): marker outlives the process, the respawned
                # group serves the epoch replay to completion
                with open(marker, "w") as f:
                    f.write(str(emitted_total))
                _record_drain(recorder, sched, replica)
                recorder.flush()
                metrics.flush()
                flight.persist()
                os.kill(os.getpid(), signal.SIGKILL)
            if ((state["draining"] or state["stop"] == "finish")
                    and not sched.busy()):
                break
            if not do_tick and not cmds:
                time.sleep(0.004)
        if cursor_w is not None:
            cursor_w.end()
            cursor_w.close()
    else:
        # follower: replay the leader's iteration journal verbatim —
        # no policy, no emissions, just lockstep state + the SPMD step
        while True:
            rec = cursor_r.next()
            if rec is None:
                time.sleep(0.004)
                continue
            if rec.get("end"):
                break
            target = int(rec["seq"])
            cmds = reader.take_upto(target)
            while reader.last_seq < target:
                # the command file is written before the cursor record,
                # but a shared-FS reader can still lag — wait it out
                time.sleep(0.002)
                cmds.extend(reader.take_upto(target))
            for cmd in cmds:
                apply(cmd)
            if rec.get("tick"):
                run_tick()
            # lockstep state only: the LEADER owns shed emission; a
            # follower drains its identical records to bound the list
            sched.take_sheds()  # rlt: disable=RLT505
    _record_drain(recorder, sched, replica)
    recorder.flush()
    recorder.close()
    if metrics.enabled:
        # stamp the stream retired so the load signal stops pooling
        # this replica's stale window into LIVE pressure
        metrics.gauge("retired", 1)
        metrics.tick_end()
    metrics.close()
    flight.close()
    return {"replica": replica, "completed": len(sched.completions),
            "steps": engine.steps, "warmup_s": warm_s,
            "compile_count": engine.compile_count,
            "occupancy": sched.slot_occupancy}


# ---- the driver ------------------------------------------------------------

class _Replica:
    """One inline replica in a dynamic serving session: engine +
    scheduler + recorder and a three-state lifecycle
    (live -> draining -> stopped)."""

    __slots__ = ("id", "engine", "sched", "recorder", "state",
                 "spawned_at", "warm_s")

    def __init__(self, rid: int, engine, sched, recorder,
                 warm_s: float):
        self.id = rid
        self.engine = engine
        self.sched = sched
        self.recorder = recorder
        self.state = "live"
        self.spawned_at = time.perf_counter()
        self.warm_s = warm_s


class _ProcessReplica:
    """One PROCESS replica in a dynamic serving session: a spawn/
    respawn thread around a `runtime.WorkerGroup` of ``cfg.tp`` ranks,
    a `serve.channel.ChannelWriter` commands flow in over, and the
    driver-side assignment ledger the respawn replay is computed from.
    Same three-state lifecycle as `_Replica`."""

    __slots__ = ("id", "state", "spawned_at", "warm_s", "writer",
                 "assigned", "live_evt", "thread", "attempts",
                 "restarts", "error", "result", "acked", "warmups")

    def __init__(self, rid: int, writer):
        import threading

        self.id = rid
        self.writer = writer
        self.state = "live"
        self.spawned_at = time.perf_counter()
        self.warm_s = None
        #: requests this replica currently owns, submission order —
        #: minus completions and evictions; the respawn replay set
        self.assigned: List[Request] = []
        self.live_evt = threading.Event()
        self.thread = None
        self.attempts = 0
        self.restarts = 0
        self.error: Optional[BaseException] = None
        self.result: Optional[dict] = None
        #: highest command seq the worker acked (observability + the
        #: channel tests' replay-safety probe)
        self.acked = 0
        self.warmups: List[float] = []


class ServeDriver:
    """Multiplex request streams over ``cfg.n_replicas`` replicas.

    ``model_cfg`` is a `models.llama.LlamaConfig`; ``params`` is the
    weights pytree (inline) or a ``.npz`` path from `save_params_npz`
    (required for process replicas — the weight-reload path IS the
    respawn story). Requests are assigned round-robin at submission;
    on replica death the unfinished remainder replays on the respawned
    replica.
    """

    def __init__(self, model_cfg, params, cfg: ReplicaGroupConfig,
                 draft_params=None):
        self.model_cfg = model_cfg
        self.cfg = cfg
        self.params = params
        self.params_path = params if isinstance(params, str) else None
        if cfg.backend == "process" and self.params_path is None:
            raise ValueError(
                "process replicas need a params .npz path "
                "(save_params_npz) — the respawn path reloads from it")
        if (cfg.draft_model_cfg is not None) != (draft_params is not None):
            raise ValueError(
                "cfg.draft_model_cfg and draft_params arm together — "
                "pass both (speculative inline replicas) or neither")
        self.draft_params = draft_params
        # ---- dynamic serving session state (docs/AUTOSCALE.md) ----
        self._session_active = False
        self.replicas: Dict[int, "_Replica"] = {}
        self._next_replica = 0
        self._rr = 0
        #: requests with no live replica to route to — the structured
        #: deferral queue (never round-robined onto a draining replica)
        self.pending: Optional[deque] = None
        self.outputs = {}
        self.meta = {}
        self.last_deferral: Optional[dict] = None
        self._spawn_faults: List[dict] = []
        self.last_spawn_s: Optional[float] = None
        self.driver_metrics = None
        self.driver_flight = None

    def _metrics_cfg(self) -> dict:
        return {"enabled": self.cfg.metrics,
                "flush_every": self.cfg.metrics_flush_every_n_ticks,
                "flight_ring": self.cfg.flight_ring,
                "flight_persist_every": self.cfg.flight_persist_every}

    def _slo_kw(self) -> Optional[dict]:
        return (self.cfg.slo.to_wire()
                if self.cfg.slo is not None else None)

    # ---- inline ----------------------------------------------------------

    def _run_inline(self, requests: Sequence[Request],
                    fault: Optional[dict]) -> ServeResult:
        from ray_lightning_tpu.models.llama import Llama

        params = self.params
        if self.params_path is not None:
            params = load_params_npz(self.params_path)
        model = Llama(self.model_cfg)
        draft_model = (Llama(self.cfg.draft_model_cfg)
                       if self.cfg.draft_model_cfg is not None else None)
        outputs: Dict[str, List[int]] = {}
        meta: Dict[str, dict] = {}
        stats_occ: List[float] = []
        t0 = time.perf_counter()
        n_tokens = 0
        scheds = []
        recorders = []
        mc = self._metrics_cfg()
        for r in range(self.cfg.n_replicas):
            metrics = _make_metrics(self.cfg.run_dir, r,
                                    enabled=mc["enabled"],
                                    flush_every=mc["flush_every"])
            flight = _make_flight(
                self.cfg.run_dir, r, enabled=mc["enabled"],
                maxlen=mc["flight_ring"],
                persist_every=mc["flight_persist_every"])
            engine = DecodeEngine(model, params, self.cfg.engine,
                                  metrics=metrics,
                                  draft_model=draft_model,
                                  draft_params=self.draft_params)
            engine.warmup()
            sched = Scheduler(engine, reserve=self.cfg.reserve,
                              metrics=metrics, flight=flight,
                              slo=self.cfg.slo)
            scheds.append(sched)
            recorders.append(_make_recorder(self.cfg.run_dir, r))

        def note_sheds(r: int, sched) -> None:
            # typed terminal status for every shed stream — a shed
            # request is never silently absent from the result (RLT505)
            for rec in sched.take_sheds():
                meta[rec["rid"]] = {
                    "replica": r, "finish_reason": "shed",
                    **{k: v for k, v in rec.items() if k != "rid"}}

        for i, req in enumerate(requests):
            scheds[i % len(scheds)].submit(req)
            outputs[req.rid] = []
        for r, sched in enumerate(scheds):
            note_sheds(r, sched)
        # round-robin tick until every replica drains — the inline
        # analog of replicas running concurrently
        while any(s.busy() for s in scheds):
            for r, sched in enumerate(scheds):
                if not sched.busy():
                    continue
                completions = sched.tick()
                for detail in sched.last_preemption_details:
                    # the replay resends from scratch; the discarded
                    # prefix is accounted as a replayed-tagged span
                    outputs[detail["rid"]] = []
                    _record_preemption(recorders[r], detail, r)
                for rid, tok in sched.last_emissions:
                    outputs[rid].append(tok)
                    n_tokens += 1
                for comp in completions:
                    _record_completion(recorders[r], comp, r)
                    meta[comp.rid] = {
                        "replica": r,
                        "finish_reason": comp.finish_reason,
                        "queue_wait_s": comp.queue_wait_s,
                        "ttft_s": comp.ttft_s, "tpot_s": comp.tpot_s,
                        "preempted": comp.preempted,
                        "n_tokens": len(comp.tokens),
                        "priority": comp.priority,
                    }
                note_sheds(r, sched)
        wall = time.perf_counter() - t0
        for r, sched in enumerate(scheds):
            stats_occ.append(sched.slot_occupancy)
            _record_drain(recorders[r], sched, r)
            recorders[r].flush()
            recorders[r].close()
            sched.metrics.close()
            sched.flight.close()
        stats = {
            "decode_tokens_per_s": n_tokens / max(wall, 1e-9),
            "slot_occupancy": float(np.mean(stats_occ)),
            "n_requests": len(requests), "n_tokens": n_tokens,
            "wall_s": wall,
            "compile_count": max(s.engine.compile_count for s in scheds),
            "requests_shed": sum(
                1 for m in meta.values()
                if m.get("finish_reason") == "shed"),
        }
        result = ServeResult(outputs=outputs, meta=meta,
                             restarts={r: 0 for r in
                                       range(self.cfg.n_replicas)},
                             stats=stats)
        self._write_summary(result)
        return result

    # ---- process replicas ------------------------------------------------

    def _run_process(self, requests: Sequence[Request],
                     fault: Optional[dict]) -> ServeResult:
        import threading

        from ray_lightning_tpu.resilience.policy import classify_failure
        from ray_lightning_tpu.runtime.group import WorkerGroup

        cfgkw = dataclasses.asdict(self.model_cfg)
        cfgkw["dtype"] = np.dtype(self.model_cfg.dtype).name
        enginekw = dataclasses.asdict(self.cfg.engine)
        n = self.cfg.n_replicas
        assign: List[List[Request]] = [[] for _ in range(n)]
        outputs: Dict[str, List[int]] = {}
        meta: Dict[str, dict] = {}
        for i, req in enumerate(requests):
            assign[i % n].append(req)
            outputs[req.rid] = []
        restarts = {r: 0 for r in range(n)}
        errors: List[BaseException] = []
        lock = san_lock("serve.driver.batch")
        fault_dir = self.cfg.run_dir or os.path.join(
            os.getcwd(), "rlt_logs", "serve")
        os.makedirs(fault_dir, exist_ok=True)
        t0 = time.perf_counter()
        token_count = [0]
        warmups: Dict[int, List[float]] = {r: [] for r in range(n)}
        occupancy: Dict[int, float] = {}
        compile_counts: Dict[int, int] = {}

        def on_queue_item(_rank, item):
            kind = item[0]
            with lock:
                if kind == "tok":
                    _, _rep, rid, tok = item
                    outputs[rid].append(tok)
                    token_count[0] += 1
                elif kind == "preempt":
                    # scheduler-level preemption: the replay resends
                    # the stream from scratch — drop the prefix
                    outputs[item[2]] = []
                elif kind == "done":
                    _, rep, rid, m = item
                    meta[rid] = {"replica": rep, **m}
                elif kind == "shed":
                    # typed terminal status: the shed stream ends with
                    # an explicit record, never silence (RLT505); the
                    # respawn replay filters on meta, so a shed rid is
                    # terminal and never double-counted
                    _, rep, rid, rec = item
                    meta[rid] = {
                        "replica": rep, "finish_reason": "shed",
                        **{k: v for k, v in rec.items()
                           if k != "rid"}}
                    outputs[rid] = []
                elif kind == "live":
                    warmups[item[1]].append(item[2]["warmup_s"])

        def run_replica(r: int) -> None:
            remaining = list(assign[r])
            rep_fault = (fault if fault and
                         fault.get("replica", 0) == r else None)
            while True:
                with lock:
                    remaining = [q for q in remaining
                                 if q.rid not in meta]
                    for q in remaining:
                        # drop partial streams of requests the dead
                        # replica had in flight — replay regenerates
                        # them bitwise from the seed
                        outputs[q.rid] = []
                if not remaining:
                    return
                group = WorkerGroup(
                    num_workers=1, env=dict(self.cfg.env or {}),
                    log_dir=os.path.join(fault_dir, f"replica{r}"),
                    start_timeout=self.cfg.start_timeout)
                try:
                    group.start()
                    res = group.run(
                        _replica_worker_main,
                        shared_args=(
                            dict(cfgkw), self.params_path,
                            dict(enginekw), self.cfg.reserve,
                            [_req_dict(q) for q in remaining], r,
                            self.cfg.run_dir,
                            self.cfg.compile_cache_dir, rep_fault,
                            fault_dir, self._metrics_cfg(),
                            self._slo_kw()),
                        on_queue_item=on_queue_item)
                    with lock:
                        occupancy[r] = res[0]["occupancy"]
                        compile_counts[r] = res[0]["compile_count"]
                    return
                except Exception as exc:  # noqa: BLE001 — classified below
                    fc = classify_failure(exc)
                    log.warning(
                        "serve replica %d died (%s/%s): %s", r, fc.kind,
                        fc.cause, fc.detail)
                    respawning = (fc.restartable
                                  and restarts[r] < self.cfg.max_restarts)
                    # flight-recorder postmortem: the dead worker's last
                    # cadence-persisted ring, stamped with the
                    # resilience classification — the SIGKILL drill's
                    # readable last-N-ticks record next to the log tail
                    if self.cfg.run_dir and self.cfg.metrics:
                        from ray_lightning_tpu.telemetry.metrics import (
                            finalize_flight,
                        )

                        finalize_flight(
                            os.path.join(self.cfg.run_dir, "telemetry"),
                            r,
                            {"kind": fc.kind, "cause": fc.cause,
                             "detail": fc.detail,
                             "restartable": fc.restartable,
                             "restarts_so_far": restarts[r],
                             "respawning": respawning},
                            os.path.join(self.cfg.run_dir,
                                         "flight.json"))
                    if not respawning:
                        with lock:
                            errors.append(exc)
                        return
                    restarts[r] += 1
                finally:
                    group.shutdown()

        threads = [threading.Thread(target=run_replica, args=(r,),
                                    daemon=True) for r in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        wall = time.perf_counter() - t0
        warm_all = [w for ws in warmups.values() for w in ws]
        stats = {
            "decode_tokens_per_s": token_count[0] / max(wall, 1e-9),
            "slot_occupancy": (float(np.mean(list(occupancy.values())))
                               if occupancy else None),
            "n_requests": len(requests), "n_tokens": token_count[0],
            "wall_s": wall,
            "warmup_cold_s": warm_all[0] if warm_all else None,
            "warmup_respawn_s": (max(warm_all[1:]) if len(warm_all) > 1
                                 else None),
            "compile_count": (max(compile_counts.values())
                              if compile_counts else None),
            "restarts_total": sum(restarts.values()),
            "requests_shed": sum(
                1 for m in meta.values()
                if m.get("finish_reason") == "shed"),
        }
        result = ServeResult(outputs=outputs, meta=meta,
                             restarts=restarts, stats=stats)
        self._write_summary(result)
        return result

    # ---- entry -----------------------------------------------------------

    def run(self, requests: Sequence[Request],
            fault: Optional[dict] = None) -> ServeResult:
        """Serve ``requests`` to completion. ``fault`` (process backend
        only): ``{"replica": r, "kill_after_tokens": n}`` SIGKILLs
        replica ``r`` once, mid-stream — the recovery drill."""
        if self.cfg.tp > 1:
            raise ValueError(
                "tp > 1 replicas are dynamic-session only (start()/"
                "submit()/stop()): the fixed-batch run() ships its "
                "request list at spawn and stays tp=1")
        # COPY before stamping: mutating the caller's Request objects
        # would make a reused request list carry the previous run's
        # arrival stamps, silently inflating every queue_wait/TTFT of
        # the next run (review finding, test-pinned)
        requests = [dataclasses.replace(r) for r in requests]
        now = time.perf_counter()
        for req in requests:
            if req.arrival == 0.0:
                req.arrival = now
        if self.cfg.backend == "inline":
            if fault:
                raise ValueError("fault injection needs "
                                 "backend='process' — a replica must "
                                 "die for real to drill recovery")
            return self._run_inline(requests, fault)
        return self._run_process(requests, fault)

    # ---- dynamic serving session: the autoscale actuation seams ----------
    # (docs/AUTOSCALE.md). `run()` above serves a FIXED batch over a
    # FIXED replica set; the session below keeps the driver live so a
    # controller can add/remove replicas while requests flow. Inline
    # replicas tick inside the driver's process; PROCESS replicas are
    # worker groups of ``cfg.tp`` ranks fed over the request channel
    # (serve/channel.py): submit/drain/stop commands flow IN over a
    # per-replica command log, results and acks batch back over the
    # side channel, and replica death replays the unfinished
    # assignment on a fresh channel epoch (docs/SERVING.md "the
    # request channel").

    def _require_session(self) -> None:
        if not self._session_active:
            raise RuntimeError(
                "no serving session — call ServeDriver.start() first "
                "(run() is the fixed-batch mode and has no scaling "
                "seams)")

    @property
    def live_ids(self) -> List[int]:
        return sorted(r.id for r in self.replicas.values()
                      if r.state == "live")

    @property
    def n_live(self) -> int:
        return len(self.live_ids)

    @property
    def n_draining(self) -> int:
        return sum(1 for r in self.replicas.values()
                   if r.state == "draining")

    def start(self, fault: Optional[dict] = None) -> "ServeDriver":
        """Open a dynamic serving session with ``cfg.n_replicas``
        replicas (each through `add_replica` — the scale-up path is the
        boot path). Requests then arrive via `submit()` and the caller
        drives `tick()`; `stop()` drains and writes serving.json.

        ``backend="process"``: each replica is a worker group fed over
        the request channel (serve/channel.py) — submit/drain/stop
        commands flow in over a per-replica command log, results and
        acks batch back over the side channel, and replica death
        replays the unfinished assignment on a fresh channel epoch.
        ``fault`` (process only): ``{"replica": r, "kill_after_tokens":
        n}`` SIGKILLs replica ``r``'s leader once, mid-stream — the
        session twin of `run()`'s recovery drill."""
        if self._session_active:
            raise RuntimeError("session already started")
        if fault and self.cfg.backend != "process":
            raise ValueError("fault injection needs backend='process' "
                             "— a replica must die for real to drill "
                             "recovery")
        if self.cfg.compile_cache_dir:
            from ray_lightning_tpu.pipeline.compile_cache import (
                enable_persistent_cache,
            )

            enable_persistent_cache(self.cfg.compile_cache_dir)
        if self.cfg.backend == "inline":
            from ray_lightning_tpu.models.llama import Llama

            self._model = Llama(self.model_cfg)
            self._draft_model = (
                Llama(self.cfg.draft_model_cfg)
                if self.cfg.draft_model_cfg is not None else None)
        else:
            self._session_dir = self.cfg.run_dir or os.path.join(
                os.getcwd(), "rlt_logs", "serve")
            os.makedirs(self._session_dir, exist_ok=True)
            self._session_fault = fault
            self._proc_lock = san_lock("serve.driver.session")
        self._session_active = True
        self.replicas = {}
        self._next_replica = 0
        self._rr = 0
        self.pending = deque()
        self.outputs = {}
        self.meta = {}
        self.last_deferral = None
        self.last_spawn_s = None
        self._session_t0 = time.perf_counter()
        self._session_tokens = 0
        self._session_ticks = 0
        mc = self._metrics_cfg()
        if self.cfg.run_dir is not None and mc["enabled"]:
            from ray_lightning_tpu.telemetry.metrics import (
                FlightRecorder, MetricsRegistry,
            )

            tdir = os.path.join(self.cfg.run_dir, "telemetry")
            self.driver_metrics = MetricsRegistry(
                tdir, replica=0, prefix="driver",
                flush_every_n_ticks=mc["flush_every"])
            self.driver_flight = FlightRecorder(
                os.path.join(tdir, "driver.flight.json"), replica=-1,
                maxlen=mc["flight_ring"],
                persist_every=mc["flight_persist_every"])
        else:
            from ray_lightning_tpu.telemetry.metrics import (
                NULL_FLIGHT, NULL_METRICS,
            )

            self.driver_metrics = NULL_METRICS
            self.driver_flight = NULL_FLIGHT
        for _ in range(self.cfg.n_replicas):
            self.add_replica()
        return self

    def inject_spawn_faults(self, count: int = 1,
                            signal_name: str = "SIGKILL") -> None:
        """Test/drill seam: the next ``count`` `add_replica` calls die
        with a real `runtime.WorkerError` carrying ``signal_name``
        death metadata — byte-for-byte what a worker SIGKILLed during
        spawn/warmup surfaces, so the controller's
        classify-retry-within-budget path is exercised without needing
        a process backend (the autoscale --smoke drill)."""
        self._spawn_faults.extend(
            {"signal_name": signal_name} for _ in range(count))

    def add_replica(self) -> int:
        """Spawn one replica NOW — exactly the respawn path: params
        reload from the .npz (when serving from a file), the step
        compiled or DESERIALIZED through the persistent compile cache
        (`pipeline.compile_cache`, armed at `start()`), then the
        replica is live and routable. Returns the replica id. Raises
        whatever the spawn raised (a `WorkerError` for worker-shaped
        deaths) — the controller classifies it via `resilience.policy`
        and retries within its budget."""
        self._require_session()
        r = self._next_replica
        if self._spawn_faults:
            fault = self._spawn_faults.pop(0)
            from ray_lightning_tpu.runtime.group import WorkerError

            self.driver_flight.record("spawn_fault", replica=r,
                                      **fault)
            raise WorkerError(
                r, "injected spawn fault: replica worker killed "
                   "during warmup (autoscale drill)",
                signal_name=fault["signal_name"], cause="signal")
        if self.cfg.backend == "process":
            return self._add_replica_process(r)
        t0 = time.perf_counter()
        params = (load_params_npz(self.params_path)
                  if self.params_path is not None else self.params)
        mc = self._metrics_cfg()
        metrics = _make_metrics(self.cfg.run_dir, r,
                                enabled=mc["enabled"],
                                flush_every=mc["flush_every"])
        flight = _make_flight(self.cfg.run_dir, r,
                              enabled=mc["enabled"],
                              maxlen=mc["flight_ring"],
                              persist_every=mc["flight_persist_every"])
        engine = DecodeEngine(self._model, params, self.cfg.engine,
                              metrics=metrics,
                              draft_model=self._draft_model,
                              draft_params=self.draft_params)
        engine.warmup()
        sched = Scheduler(engine, reserve=self.cfg.reserve,
                          metrics=metrics, flight=flight,
                          slo=self.cfg.slo)
        recorder = _make_recorder(self.cfg.run_dir, r)
        warm_s = time.perf_counter() - t0
        self._next_replica += 1
        self.replicas[r] = _Replica(r, engine, sched, recorder, warm_s)
        self.last_spawn_s = warm_s
        self.driver_metrics.count("replicas_spawned")
        self.driver_flight.record("spawn", replica=r,
                                  warm_s=round(warm_s, 4),
                                  live=self.n_live)
        # give already-queued backlog to the new replica: queued work
        # has no partial state, so redistribution is bitwise-neutral
        # (per-request seeds make every stream placement-independent)
        self._rebalance()
        return r

    def remove_replica(self, replica: Optional[int] = None,
                       graceful: bool = True) -> int:
        """Retire one replica. ``graceful`` (the default): stop
        admissions to the victim, requeue its still-queued/preempted
        work onto survivors (the bitwise replay seam — nothing partial
        exists for queued work), and let its decoding slots drain to
        retirement over subsequent `tick()`s before the worker stops.
        ``graceful=False``: additionally evict the slotted requests for
        replay elsewhere (partial streams dropped exactly like
        replica-death replay) and stop immediately. Returns the victim
        id (default: the newest live replica)."""
        self._require_session()
        if self.cfg.backend == "process":
            sends: list = []
            with self._proc_lock:
                victim = self._remove_replica_process(replica, graceful,
                                                      sends)
            self._flush_sends(sends)
            return victim
        if replica is None:
            live = self.live_ids
            if not live:
                raise RuntimeError("no live replica to remove")
            replica = live[-1]
        rep = self.replicas.get(replica)
        if rep is None or rep.state != "live":
            raise ValueError(
                f"replica {replica} is "
                f"{'unknown' if rep is None else rep.state} — only a "
                "live replica can be removed")
        rep.state = "draining"
        rep.sched.begin_drain()
        self.driver_metrics.count("replicas_drain_begun")
        self.driver_flight.record(
            "drain_begin", replica=replica, graceful=graceful,
            queued=len(rep.sched.queue), slotted=len(rep.sched.slots))
        self._requeue_from(rep)
        if not graceful:
            # account the partial wall first (inflight-tagged spans),
            # THEN evict: the replayed streams regenerate bitwise from
            # their seeds on whichever survivor admits them
            _record_drain(rep.recorder, rep.sched, replica)
            for req, preempts in rep.sched.evict_slotted():
                self.outputs[req.rid] = []
                self._route(req, preempts)
            self._stop_replica(rep)
        return replica

    def submit(self, req: Request) -> Optional[int]:
        """Route one request to a live replica (round-robin). When
        EVERY replica is draining or dead the request defers with a
        structured reason (`last_deferral`, the driver metrics
        ``submit_deferrals`` counter, a flight event) instead of
        round-robining onto a stopping replica — deferred requests
        re-route at the next `tick()` that finds a live replica.
        Returns the replica id, or None when deferred."""
        self._require_session()
        from ray_lightning_tpu.serve.scheduler import validate_request

        # validate BEFORE routing/deferring: the deferral path never
        # reaches Scheduler.submit, and an unsatisfiable span enqueued
        # raw would head-of-line-block its replica forever (it can
        # never admit) — refuse it here like the fixed-batch path does
        validate_request(self.cfg.engine, self.cfg.engine.pool_spec,
                         req)
        req = dataclasses.replace(req)
        if req.arrival == 0.0:
            req.arrival = time.perf_counter()
        if self.cfg.backend == "process":
            # the side-channel fan-in threads mutate outputs/assigned
            # under the same lock; the channel append itself happens
            # after the lock drops
            sends: list = []
            with self._proc_lock:
                self.outputs.setdefault(req.rid, [])
                target = self._route(req, 0, sends)
            self._flush_sends(sends)
            return target
        self.outputs.setdefault(req.rid, [])
        return self._route(req, 0)

    def tick(self) -> List[Completion]:
        """One serving tick across the replica set: flush deferred
        requests to any live replica, evict draining replicas' queues
        onto survivors, tick every non-stopped replica, retire drains
        that completed. Idle live replicas still tick (their gauges
        keep the load signal honest about spare capacity).

        Process backend: replicas tick themselves (the worker's own
        loop) — the driver's tick flushes deferred requests, surfaces
        any terminal replica error, and stamps the driver gauges;
        completions land in ``.meta``/``.outputs`` asynchronously and
        the return value is always empty."""
        self._require_session()
        if self.cfg.backend == "process":
            sends: list = []
            with self._proc_lock:
                for rep in self.replicas.values():
                    if rep.error is not None:
                        raise rep.error
                self._route_pending(sends)
                self._session_ticks += 1
                dm = self.driver_metrics
                if dm.enabled:
                    dm.gauge("replicas_live", self.n_live)
                    dm.gauge("replicas_draining", self.n_draining)
                    dm.gauge("pending_requests", len(self.pending))
                    dm.tick_end()
            self._flush_sends(sends)
            return []
        self._route_pending()
        done: List[Completion] = []
        for r in sorted(self.replicas):
            rep = self.replicas[r]
            if rep.state == "stopped":
                continue
            if rep.state == "draining":
                # growth-stall preemptions land back in its queue;
                # admissions are closed there, so move them out
                self._requeue_from(rep)
                if not rep.sched.slots and not rep.sched.queue:
                    self._stop_replica(rep)
                    continue
            completions = rep.sched.tick()
            for detail in rep.sched.last_preemption_details:
                self.outputs[detail["rid"]] = []
                _record_preemption(rep.recorder, detail, r)
            if rep.state == "draining":
                # a preemption during the drain tick: reroute now so
                # the request is not parked behind closed admissions
                self._requeue_from(rep)
            for rid, tok in rep.sched.last_emissions:
                self.outputs[rid].append(tok)
                self._session_tokens += 1
            for comp in completions:
                _record_completion(rep.recorder, comp, r)
                self.meta[comp.rid] = {
                    "replica": r,
                    "finish_reason": comp.finish_reason,
                    "queue_wait_s": comp.queue_wait_s,
                    "ttft_s": comp.ttft_s, "tpot_s": comp.tpot_s,
                    "preempted": comp.preempted,
                    "n_tokens": len(comp.tokens),
                    "priority": comp.priority,
                }
                if len(rep.sched.completions) % \
                        FLUSH_EVERY_N_COMPLETIONS == 0:
                    rep.recorder.flush()
            self._drain_sheds(r, rep.sched)
            done.extend(completions)
        self._session_ticks += 1
        dm = self.driver_metrics
        if dm.enabled:
            dm.gauge("replicas_live", self.n_live)
            dm.gauge("replicas_draining", self.n_draining)
            dm.gauge("pending_requests", len(self.pending))
            dm.tick_end()
        return done

    def busy(self) -> bool:
        self._require_session()
        if self.cfg.backend == "process":
            with self._proc_lock:
                return bool(self.pending) or any(
                    rep.assigned for rep in self.replicas.values()
                    if rep.state != "stopped")
        return bool(self.pending) or any(
            rep.sched.busy() for rep in self.replicas.values()
            if rep.state != "stopped")

    def force_flight_persist(self) -> int:
        """Incident-capture seam (telemetry/incidents.py,
        docs/OBSERVABILITY.md "incident capture"): persist every
        non-stopped replica's flight ring plus the driver ring NOW,
        instead of waiting out the persist cadence — a watch-rule
        breach self-documents with the breach window's final ticks on
        disk even if the process dies next. Host-side file writes
        only; returns how many rings landed. Safe outside a session
        (the fixed-batch ``run()`` owns its recorders internally):
        persists whatever the driver holds, possibly nothing."""
        persisted = 0
        for rep in self.replicas.values():
            if rep.state == "stopped":
                continue
            sched = getattr(rep, "sched", None)
            if sched is None:
                # process replicas persist worker-side on their own
                # cadence; the driver holds no ring for them
                continue
            fl = sched.flight
            if getattr(fl, "enabled", False):
                fl.persist()
                persisted += 1
        fl = self.driver_flight
        if fl is not None and getattr(fl, "enabled", False):
            fl.persist()
            persisted += 1
        return persisted

    def stop(self, drain: bool = True) -> ServeResult:
        """End the session. ``drain`` ticks until every stream
        completes first; ``drain=False`` accounts in-flight work as
        inflight-tagged spans and stops cold. Writes serving.json and
        returns the session's ServeResult."""
        self._require_session()
        if self.cfg.backend == "process":
            return self._stop_process(drain)
        if drain:
            while self.busy():
                # work can defer INTO pending mid-drain (a draining
                # replica's growth-stall preemption with no live
                # survivor): once pending is the ONLY work left and no
                # replica can ever take it, ticking forever would hang
                # here — refuse loudly instead (review finding,
                # test-pinned)
                others_busy = any(
                    rep.sched.busy() for rep in self.replicas.values()
                    if rep.state != "stopped")
                if self.pending and self.n_live == 0 and not others_busy:
                    raise RuntimeError(
                        f"{len(self.pending)} deferred request(s) with "
                        "no live replica — add_replica() before "
                        "stop(), or stop(drain=False) to abandon them")
                self.tick()
        final_replicas = self.n_live
        for rep in self.replicas.values():
            if rep.state == "stopped":
                continue
            # enqueue-time sheds on an otherwise-idle session never saw
            # a tick — surface them before the scheduler closes
            self._drain_sheds(rep.id, rep.sched)
            _record_drain(rep.recorder, rep.sched, rep.id)
            self._stop_replica(rep)
        wall = time.perf_counter() - self._session_t0
        occ = [rep.sched.slot_occupancy
               for rep in self.replicas.values()]
        stats = {
            "decode_tokens_per_s":
                self._session_tokens / max(wall, 1e-9),
            "slot_occupancy": float(np.mean(occ)) if occ else None,
            "n_requests": len(self.outputs),
            "n_tokens": self._session_tokens,
            "wall_s": wall,
            "ticks": self._session_ticks,
            "compile_count": max(
                (rep.engine.compile_count
                 for rep in self.replicas.values()), default=None),
            "replicas_spawned": self._next_replica,
            "final_replicas": final_replicas,
            "submit_deferrals":
                self.driver_metrics.counters().get(
                    "submit_deferrals", 0),
            "requests_shed":
                self.driver_metrics.counters().get(
                    "requests_shed", 0),
            "last_spawn_s": self.last_spawn_s,
        }
        result = ServeResult(
            outputs=self.outputs, meta=self.meta,
            restarts={r: 0 for r in self.replicas}, stats=stats)
        self.driver_metrics.close()
        self.driver_flight.close()
        self._write_summary(result)
        self._session_active = False
        return result

    # ---- session internals ----------------------------------------------

    def _drain_sheds(self, r: int, sched) -> None:
        """Turn a scheduler's typed shed records into terminal stream
        statuses (finish_reason="shed" + retry-after hint) — the
        graceful-overload contract: shed work is answered, never
        silently dropped (RLT505)."""
        for rec in sched.take_sheds():
            rid = rec["rid"]
            self.meta[rid] = {
                "replica": r, "finish_reason": "shed",
                **{k: v for k, v in rec.items() if k != "rid"}}
            self.outputs[rid] = []
            self.driver_metrics.count("requests_shed")

    def _pick_replica(self) -> Optional[int]:
        live = self.live_ids
        if not live:
            return None
        target = live[self._rr % len(live)]
        self._rr += 1
        return target

    def _route(self, req: Request, preempts: int,
               sends: Optional[list] = None) -> Optional[int]:
        target = self._pick_replica()
        if target is None:
            self.pending.append((req, preempts))
            self.last_deferral = {
                "rid": req.rid,
                "reason": "no live replica: all replicas draining "
                          "or dead",
                "draining": self.n_draining,
                "pending": len(self.pending),
                "at": time.perf_counter(),
            }
            self.driver_metrics.count("submit_deferrals")
            self.driver_flight.record("submit_deferral", rid=req.rid,
                                      draining=self.n_draining,
                                      pending=len(self.pending))
            return None
        rep = self.replicas[target]
        if isinstance(rep, _ProcessReplica):
            from ray_lightning_tpu.serve.channel import request_to_wire

            # the command log IS the enqueue; the driver's assignment
            # ledger is what the respawn replay is computed from. The
            # send itself is DEFERRED to after the session lock drops
            # (_flush_sends) — every process-path caller passes `sends`
            rep.assigned.append(req)
            sends.append((rep.writer, rep.writer.epoch, "submit",
                          {"req": request_to_wire(req),
                           "preempts": preempts}))
        else:
            rep.sched.enqueue(req, preempts)
        return target

    def _route_pending(self, sends: Optional[list] = None) -> None:
        while self.pending and self.live_ids:
            req, preempts = self.pending.popleft()
            self._route(req, preempts, sends)

    @staticmethod
    def _flush_sends(sends: list) -> None:
        """Perform channel sends decided under the session lock, OUTSIDE
        it — the command log's per-append fsync must not serialize the
        whole driver (threadcheck RLT705). Each send is epoch-guarded:
        if its replica respawned between the locked decision and this
        append, the fresh epoch's replay already carries the command
        (computed from the same locked state), so `send_at` drops it
        instead of duplicating the stream."""
        for writer, epoch, op, payload in sends:
            writer.send_at(epoch, op, **payload)

    def _requeue_from(self, rep: "_Replica") -> None:
        for req, preempts in rep.sched.evict_queued():
            self._route(req, preempts)

    def _rebalance(self) -> None:
        """Even out queued (never-admitted) backlog across live
        replicas after a scale-up: without this, work enqueued before
        the spawn would keep draining through the old replica alone.
        Deterministic (FIFO by arrival) and bitwise-neutral (queued
        work has no partial state; streams are seed-pure)."""
        live = [self.replicas[r] for r in self.live_ids]
        if len(live) < 2:
            return
        backlog: List = []
        for rep in live:
            backlog.extend(rep.sched.evict_queued())
        if not backlog:
            return
        backlog.sort(key=lambda item: item[0].arrival)
        for i, (req, preempts) in enumerate(backlog):
            live[i % len(live)].sched.enqueue(req, preempts)

    def _stop_replica(self, rep: "_Replica") -> None:
        rep.state = "stopped"
        rep.recorder.flush()
        rep.recorder.close()
        m = rep.sched.metrics
        if m.enabled:
            # stamp the stream retired so the load signal stops
            # pooling this replica's stale window into LIVE pressure
            # (telemetry/metrics.py load_signal_from_parsed)
            m.gauge("retired", 1)
            m.tick_end()
        m.close()
        rep.sched.flight.record("drain_end", replica=rep.id)
        rep.sched.flight.close()
        self.driver_metrics.count("replicas_stopped")
        self.driver_flight.record("drain_end", replica=rep.id,
                                  live=self.n_live)

    # ---- process-session internals (the request channel) ------------------

    def _add_replica_process(self, r: int) -> int:
        """Spawn one PROCESS replica: open its command log, start its
        spawn/respawn thread, and block until the worker group reports
        live (or the spawn classifies terminal)."""
        import threading

        from ray_lightning_tpu.serve.channel import ChannelWriter

        with self._proc_lock:
            writer = ChannelWriter(self._session_dir, r)
            rep = _ProcessReplica(r, writer)
            self._next_replica += 1
            self.replicas[r] = rep
            rep.thread = threading.Thread(
                target=self._run_session_replica, args=(rep,),
                daemon=True, name=f"serve-replica-{r}")
            rep.thread.start()
        if not rep.live_evt.wait(self.cfg.start_timeout):
            with self._proc_lock:
                rep.state = "stopped"
            raise RuntimeError(
                f"replica {r} did not report live within "
                f"{self.cfg.start_timeout:.0f}s (spawn/warmup hang) — "
                f"worker logs under {self._session_dir}/replica{r}")
        with self._proc_lock:
            if rep.error is not None:
                raise rep.error
            self.driver_metrics.count("replicas_spawned")
            self.driver_flight.record(
                "spawn", replica=r,
                warm_s=round(rep.warm_s or 0.0, 4), live=self.n_live)
        # no _rebalance across process replicas: queued work already
        # shipped over a channel cannot be pulled back without an
        # evict-back command (docs/SERVING.md "sharded replicas") —
        # NEW submissions round-robin onto the grown set immediately
        return r

    def _remove_replica_process(self, replica: Optional[int],
                                graceful: bool, sends: list) -> int:
        """Caller holds ``_proc_lock``. The drain/stop command does the
        rest: the worker evicts what the survivors should replay (its
        queue; plus its slots when not graceful), wires the evictions
        back in its final batch items, and exits; the spawn thread then
        flips the replica to stopped."""
        if replica is None:
            live = self.live_ids
            if not live:
                raise RuntimeError("no live replica to remove")
            replica = live[-1]
        rep = self.replicas.get(replica)
        if rep is None or rep.state != "live":
            raise ValueError(
                f"replica {replica} is "
                f"{'unknown' if rep is None else rep.state} — only a "
                "live replica can be removed")
        rep.state = "draining"
        self.driver_metrics.count("replicas_drain_begun")
        self.driver_flight.record(
            "drain_begin", replica=replica, graceful=graceful,
            outstanding=len(rep.assigned))
        if graceful:
            sends.append((rep.writer, rep.writer.epoch, "drain", {}))
        else:
            sends.append((rep.writer, rep.writer.epoch, "stop",
                          {"mode": "hard"}))
        return replica

    def _run_session_replica(self, rep: "_ProcessReplica") -> None:
        """One replica's spawn/respawn loop (its own thread, mirroring
        `_run_process.run_replica`): compute the channel-epoch replay,
        run the WorkerGroup of ``cfg.tp`` ranks as an SPMD program,
        classify deaths via `resilience.policy`, respawn the WHOLE
        group within the restart budget."""
        from ray_lightning_tpu.resilience.policy import classify_failure
        from ray_lightning_tpu.runtime.group import (
            WorkerGroup, find_free_port,
        )
        from ray_lightning_tpu.runtime.launch import _spmd_main
        from ray_lightning_tpu.serve.channel import request_to_wire

        cfgkw = dataclasses.asdict(self.model_cfg)
        cfgkw["dtype"] = np.dtype(self.model_cfg.dtype).name
        enginekw = dataclasses.asdict(self.cfg.engine)
        tp = self.cfg.tp
        fault = getattr(self, "_session_fault", None)
        rep_fault = (fault if fault and
                     fault.get("replica", 0) == rep.id else None)
        while True:
            with self._proc_lock:
                if rep.attempts > 0:
                    # respawn: a FRESH epoch replaying the unfinished
                    # assignment + control state. Partial streams drop
                    # here — the replay regenerates them bitwise from
                    # the per-request seeds (scheduler purity)
                    rep.assigned = [q for q in rep.assigned
                                    if q.rid not in self.meta]
                    # partial prefixes are NOT cleared here: the
                    # respawned worker announces every replayed submit
                    # it admits ("starts" in its first batch) and the
                    # fan-in resets the stream there — keeps this
                    # thread's hands off the driver's result dicts
                    replay = [{"op": "submit", "req": request_to_wire(q)}
                              for q in rep.assigned]
                    if rep.state == "draining":
                        replay.append({"op": "drain"})
                    rep.writer.begin_epoch(replay)
                epoch = rep.writer.epoch
            group = WorkerGroup(
                num_workers=tp, env=dict(self.cfg.env or {}),
                log_dir=os.path.join(self._session_dir,
                                     f"replica{rep.id}"),
                start_timeout=self.cfg.start_timeout)
            try:
                group.start()
                coordinator = f"127.0.0.1:{find_free_port()}"
                res = group.run(
                    _spmd_main,
                    shared_args=(
                        _replica_session_main,
                        (dict(cfgkw), self.params_path, dict(enginekw),
                         self.cfg.reserve, rep.id, self.cfg.run_dir,
                         self._session_dir, self.cfg.compile_cache_dir,
                         rep_fault, self._session_dir,
                         self._metrics_cfg(), epoch, tp,
                         self._slo_kw()),
                        {}, tp, coordinator, self.cfg.platform,
                        self.cfg.cpu_devices_per_rank),
                    per_rank_args=[(k, (k,)) for k in range(tp)],
                    on_queue_item=self._on_session_item)
                with self._proc_lock:
                    rep.result = res[0]
                    if rep.state != "stopped":
                        self._finalize_process_replica(rep)
                return
            except Exception as exc:  # noqa: BLE001 — classified below
                fc = classify_failure(exc)
                log.warning(
                    "session replica %d died (%s/%s): %s", rep.id,
                    fc.kind, fc.cause, fc.detail)
                with self._proc_lock:
                    respawning = (fc.restartable
                                  and rep.restarts < self.cfg.max_restarts
                                  and rep.state != "stopped")
                    if self.cfg.run_dir and self.cfg.metrics:
                        from ray_lightning_tpu.telemetry.metrics import (
                            finalize_flight,
                        )

                        finalize_flight(
                            os.path.join(self.cfg.run_dir, "telemetry"),
                            rep.id,
                            {"kind": fc.kind, "cause": fc.cause,
                             "detail": fc.detail,
                             "restartable": fc.restartable,
                             "restarts_so_far": rep.restarts,
                             "respawning": respawning},
                            os.path.join(self.cfg.run_dir,
                                         "flight.json"))
                    rep.attempts += 1
                    if not respawning:
                        rep.error = exc
                        rep.state = "stopped"
                        rep.live_evt.set()
                        return
                    rep.restarts += 1
                    rep.live_evt.clear()
            finally:
                group.shutdown()

    def _on_session_item(self, _rank, item) -> None:
        """Side-channel fan-in for every session replica (called from
        their spawn threads): one BATCHED item per worker tick —
        tokens, acks, completions, evictions together (the channel's
        RLT504 discipline)."""
        from ray_lightning_tpu.serve.channel import request_from_wire

        kind = item[0]
        sends: list = []
        with self._proc_lock:
            rep = self.replicas.get(item[1])
            if rep is None:
                return
            if kind == "live":
                w = item[2]["warmup_s"]
                rep.warm_s = w
                rep.warmups.append(w)
                rep.spawned_at = time.perf_counter()
                self.last_spawn_s = w
                rep.live_evt.set()
                return
            if kind != "batch":
                return
            payload = item[2]
            if "ack" in payload:
                rep.acked = max(rep.acked, int(payload["ack"]))
            for rid in payload.get("starts", ()):
                # the worker admitted this submit afresh — on a normal
                # submit a no-op reset, on an epoch replay after respawn
                # THE reset that drops the dead epoch's partial prefix
                # (the stream regenerates bitwise from its seed).
                # Ordered before toks: a replayed stream's first tokens
                # can share this batch
                self.outputs[rid] = []
            for rid in payload.get("preempts", ()):
                # scheduler-level preemption: the replay resends the
                # stream from scratch — drop the prefix
                self.outputs[rid] = []
            for rid, tok in payload.get("toks", ()):
                self.outputs[rid].append(int(tok))
                self._session_tokens += 1
            for rid, m in payload.get("dones", ()):
                self.meta[rid] = {"replica": rep.id, **m}
                rep.assigned = [q for q in rep.assigned
                                if q.rid != rid]
            for rec in payload.get("sheds", ()):
                # typed terminal status for a shed stream (RLT505) —
                # idempotent across epoch rolls: a rid already terminal
                # in meta is not re-counted, and dropping it from the
                # assignment ledger keeps the respawn replay from
                # resubmitting (and re-shedding) the dead epoch's sheds
                rid = rec["rid"]
                if (self.meta.get(rid, {}).get("finish_reason")
                        != "shed"):
                    self.driver_metrics.count("requests_shed")
                self.meta[rid] = {
                    "replica": rep.id, "finish_reason": "shed",
                    **{k: v for k, v in rec.items() if k != "rid"}}
                self.outputs[rid] = []
                rep.assigned = [q for q in rep.assigned
                                if q.rid != rid]
            for wire, preempts in payload.get("evicted", ()):
                # a draining/stopping replica handing work back for
                # the survivors (bitwise replay seam)
                req = request_from_wire(wire)
                rep.assigned = [q for q in rep.assigned
                                if q.rid != req.rid]
                self.outputs[req.rid] = []
                self._route(req, int(preempts), sends)
        self._flush_sends(sends)

    def _finalize_process_replica(self, rep: "_ProcessReplica") -> None:
        """Worker group exited cleanly (caller holds ``_proc_lock``).
        The worker owned and closed the replica's telemetry streams —
        the driver only flips state and stamps its own records."""
        rep.state = "stopped"
        self.driver_metrics.count("replicas_stopped")
        self.driver_flight.record("drain_end", replica=rep.id,
                                  live=self.n_live)

    def _stop_process(self, drain: bool) -> ServeResult:
        if drain:
            while self.busy():
                with self._proc_lock:
                    others_busy = any(
                        rep.assigned for rep in self.replicas.values()
                        if rep.state != "stopped")
                    if (self.pending and self.n_live == 0
                            and not others_busy):
                        raise RuntimeError(
                            f"{len(self.pending)} deferred request(s) "
                            "with no live replica — add_replica() "
                            "before stop(), or stop(drain=False) to "
                            "abandon them")
                self.tick()
                time.sleep(0.01)
        sends: list = []
        with self._proc_lock:
            final_replicas = self.n_live
            for rep in self.replicas.values():
                if rep.state != "stopped":
                    # "finish": serve out everything assigned, then
                    # exit; "abort": account in-flight work as
                    # inflight-tagged spans and exit now
                    sends.append(
                        (rep.writer, rep.writer.epoch, "stop",
                         {"mode": "finish" if drain else "abort"}))
        self._flush_sends(sends)
        for rep in self.replicas.values():
            if rep.thread is not None:
                rep.thread.join(self.cfg.start_timeout)
        for rep in self.replicas.values():
            if rep.error is not None:
                raise rep.error
        wall = time.perf_counter() - self._session_t0
        results = [rep.result for rep in self.replicas.values()
                   if rep.result]
        occ = [res["occupancy"] for res in results]
        warm_all = [w for rep in self.replicas.values()
                    for w in rep.warmups]
        stats = {
            "decode_tokens_per_s":
                self._session_tokens / max(wall, 1e-9),
            "slot_occupancy": (float(np.mean(occ)) if occ else None),
            "n_requests": len(self.outputs),
            "n_tokens": self._session_tokens,
            "wall_s": wall,
            "ticks": self._session_ticks,
            "compile_count": max(
                (res["compile_count"] for res in results),
                default=None),
            "replicas_spawned": self._next_replica,
            "final_replicas": final_replicas,
            "warmup_cold_s": warm_all[0] if warm_all else None,
            "warmup_respawn_s": (max(warm_all[1:])
                                 if len(warm_all) > 1 else None),
            "restarts_total": sum(rep.restarts
                                  for rep in self.replicas.values()),
            "submit_deferrals":
                self.driver_metrics.counters().get(
                    "submit_deferrals", 0),
            "requests_shed":
                self.driver_metrics.counters().get(
                    "requests_shed", 0),
            "last_spawn_s": self.last_spawn_s,
        }
        result = ServeResult(
            outputs=self.outputs, meta=self.meta,
            restarts={rep.id: rep.restarts
                      for rep in self.replicas.values()}, stats=stats)
        for rep in self.replicas.values():
            rep.writer.close()
        self.driver_metrics.close()
        self.driver_flight.close()
        self._write_summary(result)
        self._session_active = False
        return result

    def _write_summary(self, result: ServeResult) -> None:
        if self.cfg.run_dir is None:
            return
        os.makedirs(self.cfg.run_dir, exist_ok=True)
        from ray_lightning_tpu.telemetry.metrics import (
            aggregate_from_parsed, load_signal_from_parsed,
            newest_from_parsed, read_all_metrics,
        )

        doc = {"stats": result.stats, "meta": result.meta,
               "restarts": result.restarts}
        tdir = _serve_metrics_dir(self.cfg.run_dir)
        parsed = read_all_metrics(tdir)  # one pass feeds both rollups
        agg = aggregate_from_parsed(parsed)
        if agg is not None:
            # run-level rollup of the per-replica metric streams:
            # latency quantiles FROM MERGED BUCKETS (exact across
            # replicas/attempts), counters summed, and the rolling
            # load summary the autoscale oracle reads
            doc["metrics"] = agg
            doc["load"] = load_signal_from_parsed(
                newest_from_parsed(parsed), where=tdir)
        path = os.path.join(self.cfg.run_dir, "serving.json")
        with open(path, "w") as f:
            json.dump(doc, f, indent=2)


# ---- run-level metric aggregation + the autoscale load signal -------------


def _serve_metrics_dir(run_dir: str) -> str:
    tdir = os.path.join(run_dir, "telemetry")
    return tdir if os.path.isdir(tdir) else run_dir


def aggregate_serve_metrics(run_dir: str) -> Optional[dict]:
    """Merge every per-replica metrics JSONL under
    ``<run_dir>/telemetry`` into one run-level view: summed counters,
    exactly-merged latency histograms (quantiles from buckets),
    per-replica tick/attempt counts, and queue-depth/occupancy series
    stats. None when the run recorded no metrics (metrics off, or
    nothing served)."""
    from ray_lightning_tpu.telemetry.metrics import aggregate_metrics_dir

    return aggregate_metrics_dir(_serve_metrics_dir(run_dir))


def load_signal(run_dir: str, window: Optional[int] = None) -> dict:
    """The queue-depth/occupancy oracle input for replica autoscale
    (ROADMAP item 1c) and the elastic capacity oracle
    (docs/OBSERVABILITY.md "load signal").

    Reads the NEWEST metrics file per replica under
    ``<run_dir>/telemetry`` and summarizes the last ``window`` tick
    samples each flushed:

      available            False when no metrics exist yet (a caller
                           must treat that as "no signal", never zero
                           load)
      queue_depth_now      summed latest queue depth across replicas
      queue_depth_p50/max  over the recent window, all replicas pooled
      occupancy            mean decoding-slot fraction over the window
      blocks_free_fraction pool headroom (min across replicas)
      pressure             queue_depth_p50 / total_slots — > 0 means
                           demand is queuing behind capacity; the
                           dimensionless number an autoscaler compares
                           against its scale-up threshold
      replicas             per-replica {queue_depth, occupancy, ticks}

    The signal is computed from FLUSHED samples, so it lags live state
    by at most one flush cadence — the honest price of RLT501's
    no-per-tick-I/O discipline."""
    from ray_lightning_tpu.telemetry.metrics import (
        LOAD_SIGNAL_WINDOW, load_signal_from_dir,
    )

    return load_signal_from_dir(
        _serve_metrics_dir(run_dir),
        window=window if window is not None else LOAD_SIGNAL_WINDOW)


def _req_dict(req: Request) -> dict:
    return {"rid": req.rid, "prompt": np.asarray(req.prompt).tolist(),
            "max_new_tokens": req.max_new_tokens,
            "temperature": req.temperature, "top_k": req.top_k,
            "seed": req.seed, "eos_id": req.eos_id,
            "priority": req.priority}
