"""``python -m ray_lightning_tpu serve`` — the serving front-end + the
format.sh smoke gate.

    python -m ray_lightning_tpu serve example          # inline demo
    python -m ray_lightning_tpu serve example --replicas 2 \\
        --backend process                              # process replicas
    python -m ray_lightning_tpu serve llama3-8b        # static plan+audit
    python -m ray_lightning_tpu serve --smoke          # the gate

``--smoke`` (docs/SERVING.md "acceptance") is the CPU gate format.sh
runs; it fails (exit 1) unless ALL of:

  * 8 concurrent staggered streams (ragged prompts, mixed greedy /
    temperature / top-k sampling, per-request seeds) decode
    **bitwise-identical** to 8 independent single-stream `generate()`
    runs;
  * request churn across the run compiles the engine step exactly ONCE
    (compile-count pinned — no silent recompile-per-request);
  * with 2 process replicas, one injected SIGKILL mid-stream is
    classified, the replica respawns (weights reloaded, step re-warmed
    through the persistent compile cache), the lost streams replay
    bitwise, and the surviving replica's streams are untouched;
  * the METRICS legs (docs/OBSERVABILITY.md "serving metrics"): the
    8-stream run emits per-replica metrics JSONL on the tick cadence
    whose completion-histogram counts equal the completed-request
    count; histogram merge across the 2 process replicas is EXACT
    (counts sum, quantiles from the merged buckets are merge-order
    independent); the injected SIGKILL leaves a parseable
    ``flight.json`` whose dump carries the final ticks + the
    resilience classification; `load_signal()` reports; and the engine
    still compiles exactly once with metrics armed;
  * the decode step audits clean under tracecheck (no RLT301/RLT303);
  * the FUSED paged-attention path (`force_pallas` + interpret on a
    kernel-tiling tiny config): 8 concurrent streams match the
    reference-path engine token for token, churn still compiles once,
    the fused decode step audits clean with RLT307 absent and the
    paged-attention kernel actually present in the trace;
  * the FUSED paged-PREFILL path (ISSUE 15, same kernel-tiling tiny
    discipline): a ragged left-padded prefill group (prefill_batch=2,
    a chunk width that does not divide the slot length) decodes
    token-for-token equal to the reference-lane engine, churn compiles
    once, and the fused step audits clean with ZERO dense paged
    gathers at ANY nesting level (RLT307 + RLT308 absent, the
    paged-prefill kernel present in the trace);
  * the PREFIX-SHARING leg (docs/SERVING.md "prefix cache"): an
    8-stream fleet behind one common system prompt decodes bitwise vs
    per-stream `generate()` with ``shared_block_fraction > 0`` AND a
    prefill-token count STRICTLY below the same fleet served without
    the cache — the shared prefix prefilled exactly once;
  * the SPECULATIVE leg (docs/SERVING.md "speculative decoding"):
    draft+target greedy decode is TOKEN-IDENTICAL to plain greedy
    `generate()`, still at compile-count 1.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def add_serve_parser(sub) -> None:
    p = sub.add_parser(
        "serve",
        help="continuous-batching inference engine: run a demo serve, "
             "audit the decode step, or the format.sh smoke gate")
    p.add_argument("preset", nargs="?", default="example",
                   choices=("example", "llama3-8b"),
                   help="example = tiny CPU-served demo; llama3-8b = "
                        "static serve plan + decode-step audit")
    p.add_argument("--smoke", action="store_true",
                   help="gate mode (see module docstring); exit 1 on "
                        "any failed leg")
    p.add_argument("--replicas", type=int, default=1)
    p.add_argument("--backend", choices=("inline", "process"),
                   default="inline")
    p.add_argument("--requests", type=int, default=8,
                   help="synthetic demo requests")
    p.add_argument("--max-new", type=int, default=16)
    p.add_argument("--slots", type=int, default=4,
                   help="engine slot capacity per replica")
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--blocks-per-slot", type=int, default=None,
                   help="default: sized to --seq-budget")
    p.add_argument("--prefill-chunk", type=int, default=32)
    p.add_argument("--prefill-batch", type=int, default=1,
                   help="queued prompts admitted per tick through the "
                        "left-padded batched prefill lane (1 = the "
                        "historical single-slot lane)")
    p.add_argument("--seq-budget", type=int, default=4096,
                   help="llama3-8b plan: per-slot prompt+generation cap")
    p.add_argument("--run-dir", default=None,
                   help="telemetry spans + serving.json land here")
    p.add_argument("--topo", default="v5p-8",
                   help="topology for the decode-step audit")
    p.add_argument("--autotune", metavar="OUT.json", default=None,
                   help="run the block-size sweep for BOTH paged "
                        "kernels on this preset's shape and write the "
                        "winning geometry artifact (serve/sweep.py; "
                        "interpret-mode correctness everywhere, "
                        "wall-clock timing on a real TPU backend, "
                        "structured skip otherwise)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   default=argparse.SUPPRESS)


def _tiny_setup(n_requests: int, max_new: int, seed: int = 1):
    """Deterministic tiny model + ragged mixed-sampling request set —
    the same inputs the smoke legs and the demo serve."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_lightning_tpu.models.llama import Llama, LlamaConfig
    from ray_lightning_tpu.serve.scheduler import Request

    cfg = LlamaConfig.tiny(use_flash=False, dtype=jnp.float32)
    model = Llama(cfg)
    prompts = [
        np.array(jax.random.randint(
            jax.random.key(100 + i), (1, 3 + (i % 5)), 0,
            cfg.vocab_size), dtype=np.int32)
        for i in range(n_requests)
    ]
    params = jax.jit(model.init)(jax.random.key(seed), prompts[0])[
        "params"]
    reqs = []
    for i, p in enumerate(prompts):
        sampled = i % 2 == 1
        reqs.append(Request(
            rid=f"r{i}", prompt=p[0], max_new_tokens=max_new,
            temperature=0.8 if sampled else 0.0,
            top_k=5 if sampled else None, seed=31 + i))
    return cfg, model, params, prompts, reqs


def _references(model, params, prompts, reqs):
    """Independent single-stream generate() runs — the bitwise oracle."""
    import numpy as np

    from ray_lightning_tpu.models.llama import generate

    return {
        r.rid: np.asarray(generate(
            model, params, prompts[i], r.max_new_tokens,
            temperature=r.temperature, top_k=r.top_k, seed=r.seed))[0]
        for i, r in enumerate(reqs)
    }


def _check_outputs(outputs, refs) -> list:
    import numpy as np

    bad = []
    for rid, ref in refs.items():
        got = np.asarray(outputs.get(rid, []))
        if not np.array_equal(got, ref):
            bad.append(rid)
    return bad


def run_smoke(args) -> int:
    """The format.sh gate (module docstring for the leg list), all
    CPU."""
    from ray_lightning_tpu.serve.audit import audit_decode_step
    from ray_lightning_tpu.serve.driver import (
        ReplicaGroupConfig, ServeDriver, save_params_npz,
    )
    from ray_lightning_tpu.serve.engine import EngineConfig

    verdict = {"legs": {}}
    failures = []
    ecfg = EngineConfig(capacity=4, block_size=4, blocks_per_slot=8,
                        prefill_chunk=4)
    cfg, model, params, prompts, reqs = _tiny_setup(8, 8)
    refs = _references(model, params, prompts, reqs)

    # ---- leg 1: inline churn — 8 staggered streams through 4 slots,
    # metrics ARMED (the compile pin below therefore also proves
    # instrumentation does not retrace the step) ----------------------
    with tempfile.TemporaryDirectory(prefix="rlt-serve-smoke1-") as tmp1:
        run1 = os.path.join(tmp1, "run")
        drv = ServeDriver(cfg, params, ReplicaGroupConfig(
            n_replicas=1, backend="inline", engine=ecfg,
            reserve="on_demand", run_dir=run1,
            metrics_flush_every_n_ticks=4))
        res = drv.run(list(reqs))
        bad = _check_outputs(res.outputs, refs)
        compile_ok = res.stats.get("compile_count") in (1, -1)
        verdict["legs"]["inline_churn"] = {
            "bitwise_mismatches": bad,
            "compile_count": res.stats.get("compile_count"),
            "slot_occupancy": round(res.stats.get("slot_occupancy")
                                    or 0, 3),
        }
        if bad:
            failures.append(
                f"inline streams diverge from generate(): {bad}")
        if not compile_ok:
            failures.append(
                f"request churn recompiled the step (metrics armed): "
                f"compile_count={res.stats.get('compile_count')} "
                f"(want 1)")
        verdict["legs"]["metrics_emission"] = _smoke_metrics_emission(
            failures, run1, expected_completions=len(reqs))

    # ---- leg 2: process replicas + injected SIGKILL -------------------
    with tempfile.TemporaryDirectory(prefix="rlt-serve-smoke-") as tmp:
        pp = os.path.join(tmp, "params.npz")
        save_params_npz(params, pp)
        run2 = os.path.join(tmp, "run")
        drv2 = ServeDriver(cfg, pp, ReplicaGroupConfig(
            n_replicas=2, backend="process", engine=ecfg,
            run_dir=run2,
            compile_cache_dir=os.path.join(tmp, "compile_cache"),
            env={"JAX_PLATFORMS": "cpu"},
            metrics_flush_every_n_ticks=4, flight_persist_every=4))
        # the driver copies requests before stamping, so the same list
        # serves both legs without leaking leg 1's arrival times
        res2 = drv2.run(list(reqs), fault={"replica": 1,
                                           "kill_after_tokens": 6})
        bad2 = _check_outputs(res2.outputs, refs)
        verdict["legs"]["replica_kill"] = {
            "bitwise_mismatches": bad2,
            "restarts": res2.restarts,
            "compile_count": res2.stats.get("compile_count"),
        }
        if bad2:
            failures.append(
                f"streams diverge after replica kill: {bad2}")
        if res2.restarts.get(1, 0) < 1:
            failures.append(
                "the injected SIGKILL did not produce a replica "
                "restart — the drill did not run")
        # surviving replica's requests must have decoded on replica 0
        # without interruption (no restart there)
        if res2.restarts.get(0, 0) != 0:
            failures.append("the SURVIVING replica restarted too")
        verdict["legs"]["metrics_merge"] = _smoke_metrics_merge(
            failures, run2)
        verdict["legs"]["flight_recorder"] = _smoke_flight(
            failures, run2)

    # ---- leg 3: decode step audits clean ------------------------------
    report = audit_decode_step(cfg, ecfg, topology=args.topo)
    rules = sorted({f.rule for f in report.findings})
    verdict["legs"]["audit"] = {"findings": rules,
                                "peak_hbm_bytes": report.peak_hbm_bytes}
    if any(r in ("RLT301", "RLT303") for r in rules):
        failures.append(f"decode step audit findings: {rules}")

    # ---- leg 4: fused paged-attention path ----------------------------
    verdict["legs"]["fused_paged"] = _smoke_fused_leg(failures,
                                                     args.topo)

    # ---- leg 5: fused paged-PREFILL path ------------------------------
    verdict["legs"]["fused_prefill"] = _smoke_fused_prefill_leg(
        failures, args.topo)

    # ---- leg 6: prefix sharing — the common prefix prefills ONCE ------
    verdict["legs"]["prefix_sharing"] = _smoke_prefix_leg(failures)

    # ---- leg 7: speculative decode — greedy token identity ------------
    verdict["legs"]["speculative"] = _smoke_spec_leg(failures)

    verdict["ok"] = not failures
    if failures:
        verdict["failures"] = failures
    print(json.dumps(verdict))
    if failures:
        for f in failures:
            print(f"serve --smoke FAILED: {f}", file=sys.stderr)
        return 1
    return 0


def _smoke_metrics_emission(failures: list, run_dir: str,
                            expected_completions: int) -> dict:
    """Metrics leg A (docs/OBSERVABILITY.md "serving metrics"): the
    8-stream run must leave per-replica metrics JSONL on the tick
    cadence whose completion-histogram counts equal the
    completed-request count, and `load_signal()` must report."""
    from ray_lightning_tpu.serve.driver import load_signal
    from ray_lightning_tpu.telemetry.metrics import (
        metrics_paths, read_metrics,
    )

    tdir = os.path.join(run_dir, "telemetry")
    paths = metrics_paths(tdir)
    leg: dict = {"files": [os.path.basename(p) for p in paths]}
    if not paths:
        failures.append("serving left no per-replica metrics JSONL")
        return leg
    ticks = 0
    completions = 0
    hist_ns = {}
    for p in paths:
        parsed = read_metrics(p)
        ticks += len(parsed["ticks"])
        completions += int(parsed["counters"].get("completions", 0))
        for name, h in parsed["hists"].items():
            hist_ns[name] = hist_ns.get(name, 0) + h.n
    leg.update({"ticks": ticks, "completions": completions,
                "hist_counts": hist_ns})
    if ticks < 1:
        failures.append("metrics JSONL holds no tick samples — the "
                        "tick-cadence flush never fired")
    for name in ("ttft_s", "tpot_s", "queue_wait_s"):
        if hist_ns.get(name) != expected_completions:
            failures.append(
                f"histogram {name} counts {hist_ns.get(name)} != "
                f"completed-request count {expected_completions}")
    if completions != expected_completions:
        failures.append(
            f"completions counter {completions} != "
            f"{expected_completions}")
    sig = load_signal(run_dir)
    leg["load_signal"] = {k: sig.get(k) for k in
                          ("available", "queue_depth_p50",
                           "occupancy", "pressure")}
    if not sig.get("available"):
        failures.append("load_signal() reports unavailable on a run "
                        "that just served")
    return leg


def _smoke_metrics_merge(failures: list, run_dir: str) -> dict:
    """Metrics leg B: histogram merge across the 2 process replicas
    must be EXACT — counts sum as integers, and the p50/p95/p99 read
    from merged buckets is identical whichever merge order produced
    them."""
    from ray_lightning_tpu.telemetry.metrics import (
        merge_histograms, metrics_paths, read_metrics,
    )

    tdir = os.path.join(run_dir, "telemetry")
    paths = metrics_paths(tdir)
    leg: dict = {"files": [os.path.basename(p) for p in paths]}
    parts = []
    for p in paths:
        h = read_metrics(p)["hists"].get("ttft_s")
        if h is not None:
            parts.append(h)
    leg["parts"] = len(parts)
    if len(parts) < 2:
        failures.append(
            "metrics merge leg needs ttft_s histograms from >= 2 "
            f"replica files, found {len(parts)}")
        return leg
    fwd = merge_histograms(parts)
    rev = merge_histograms(list(reversed(parts)))
    leg["merged_n"] = fwd.n
    leg["sum_of_parts"] = sum(h.n for h in parts)
    leg["p99_fwd"] = fwd.quantile(0.99)
    leg["p99_rev"] = rev.quantile(0.99)
    if fwd.n != sum(h.n for h in parts):
        failures.append(
            f"merged histogram count {fwd.n} != sum of per-replica "
            f"counts {sum(h.n for h in parts)} — merge is not exact")
    if fwd.counts != rev.counts or any(
            fwd.quantile(q) != rev.quantile(q)
            for q in (0.5, 0.95, 0.99)):
        failures.append("histogram merge is order-dependent — "
                        "quantiles from merged buckets must not care "
                        "which replica's file merged first")
    return leg


def _smoke_flight(failures: list, run_dir: str) -> dict:
    """Metrics leg C: the injected SIGKILL must leave a parseable
    ``flight.json`` whose dump carries the dead replica's final ticks
    and the resilience classification the driver stamped on."""
    path = os.path.join(run_dir, "flight.json")
    leg: dict = {"path": path}
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as exc:
        failures.append(f"no parseable flight.json after the SIGKILL "
                        f"drill: {type(exc).__name__}: {exc}")
        return leg
    dumps = doc.get("dumps") or []
    leg["dumps"] = len(dumps)
    if not dumps:
        failures.append("flight.json holds no dumps")
        return leg
    dump = dumps[0]
    events = dump.get("events") or []
    tick_events = [e for e in events if e.get("kind") == "tick"]
    leg.update({
        "replica": dump.get("replica"),
        "events": len(events),
        "tick_events": len(tick_events),
        "last_tick": tick_events[-1].get("tick") if tick_events
        else None,
        "death": dump.get("death"),
    })
    if not tick_events:
        failures.append("flight dump carries no tick events — the "
                        "postmortem has no final ticks to read")
    death = dump.get("death") or {}
    if not death.get("kind"):
        failures.append("flight dump is missing the resilience "
                        "classification (death.kind)")
    return leg


def _fused_leg_harness(ecfg, *, prompt_key: int, param_key: int,
                       rid_prefix: str, temp: float, top_k: int,
                       seed_base: int, prompt_floor: int,
                       prompt_mod: int):
    """Shared harness of the two fused smoke legs: the kernel-TILING
    tiny model (head_dim 64, GQA 2:1 — the main legs' tiny model has
    head_dim 16, which both kernels correctly refuse; dispatch honesty
    is part of what the legs prove), a ragged mixed-sampling request
    set, one reference-lane run, one force_pallas run. Returns
    ``(cfg, eng, out_ref, out_fused, mismatched)`` — the legs keep
    their own audit verdicts, but the run discipline (reserve policy,
    churn shape, stream comparison) cannot drift between them."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_lightning_tpu.models.llama import Llama, LlamaConfig
    from ray_lightning_tpu.ops import dispatch
    from ray_lightning_tpu.serve.engine import DecodeEngine
    from ray_lightning_tpu.serve.scheduler import Request, Scheduler

    cfg = LlamaConfig(vocab_size=256, dim=128, n_layers=2, n_heads=2,
                      n_kv_heads=1, hidden_dim=256, max_seq_len=128,
                      remat=False, dtype=jnp.float32)
    model = Llama(cfg)
    prompts = [
        np.array(jax.random.randint(
            jax.random.key(prompt_key + i),
            (prompt_floor + (i % prompt_mod),), 0,
            cfg.vocab_size), dtype=np.int32)
        for i in range(8)
    ]
    params = jax.jit(model.init)(jax.random.key(param_key),
                                 prompts[0][None])["params"]

    def run(engine):
        sched = Scheduler(engine, reserve="on_demand")
        pend = [Request(rid=f"{rid_prefix}{i}", prompt=p,
                        max_new_tokens=8,
                        temperature=temp if i % 2 else 0.0,
                        top_k=top_k if i % 2 else None,
                        seed=seed_base + i)
                for i, p in enumerate(prompts)]
        out = {}
        while sched.busy() or pend:
            if pend:
                sched.submit(pend.pop(0))
            for comp in sched.tick():
                out[comp.rid] = comp.tokens
        return out

    ref_engine = DecodeEngine(model, params, ecfg, use_pallas=False)
    out_ref = run(ref_engine)
    with dispatch.force_pallas():
        eng = DecodeEngine(model, params, ecfg)
        out_fused = run(eng) if (eng.fused or eng.fused_prefill) \
            else {}
    mismatched = [rid for rid in out_ref
                  if out_fused.get(rid) != out_ref[rid]]
    return cfg, eng, out_ref, out_fused, mismatched


def _smoke_fused_leg(failures: list, topo: str) -> dict:
    """The fused-path smoke leg: the paged-attention kernel (interpret
    mode under `force_pallas`) must serve 8 concurrent streams token-
    for-token equal to the reference-path engine, compile once across
    churn, and audit clean (RLT307 absent — the dense view is gone)."""
    from ray_lightning_tpu.serve.audit import audit_decode_step
    from ray_lightning_tpu.serve.engine import EngineConfig

    ecfg = EngineConfig(capacity=4, block_size=8, blocks_per_slot=4,
                        prefill_chunk=4, prefill_batch=2)
    cfg, eng, out_ref, out_fused, mismatched = _fused_leg_harness(
        ecfg, prompt_key=300, param_key=7, rid_prefix="f", temp=0.8,
        top_k=5, seed_base=61, prompt_floor=3, prompt_mod=5)
    fused_selected = eng.fused
    # ONE trace serves both verdicts: the audit's findings (RLT307
    # absent here <=> no dense decode gather, since the shape tiles)
    # and the kernel fingerprint the auditor recorded walking it
    report = audit_decode_step(cfg, ecfg, topology=topo, fused=True,
                               label="fused smoke decode step")
    rules = sorted({f.rule for f in report.findings})
    kernel_in_trace = any("paged_attention" in k
                          for k in report.pallas_kernels)
    leg = {
        "fused_selected": fused_selected,
        "stream_mismatches": mismatched,
        "compile_count": eng.compile_count,
        "audit_findings": rules,
        "kernel_in_trace": kernel_in_trace,
        "attention_path": eng.attention_path,
    }
    if not fused_selected:
        failures.append("force_pallas did not select the fused paged-"
                        "attention path for a kernel-tiling shape")
        return leg
    if mismatched:
        failures.append(
            f"fused-path streams diverge from the reference path: "
            f"{mismatched}")
    if eng.compile_count not in (1, -1):
        failures.append(
            f"fused-path churn recompiled the step: compile_count="
            f"{eng.compile_count} (want 1)")
    if any(r in ("RLT301", "RLT303", "RLT307") for r in rules):
        failures.append(f"fused decode step audit findings: {rules}")
    if not kernel_in_trace:
        failures.append("the paged-attention kernel is absent from the "
                        "fused trace — the fused lane fell back to the "
                        "gathering reference op")
    return leg


def _smoke_fused_prefill_leg(failures: list, topo: str) -> dict:
    """The fused-PREFILL smoke leg (ISSUE 15): on the kernel-tiling
    tiny config, a RAGGED left-padded prefill group (prefill_batch=2
    over prompts of assorted lengths, with a chunk width that does not
    divide the slot length — the PR 8 tail-window class rides along)
    must decode token-for-token equal to the reference-lane engine,
    churn must compile once, and the fused step must audit clean with
    ZERO dense paged gathers at ANY nesting level — both the decode
    lane's capacity-wide view and the prefill lane's cond-nested
    group view are gone (`trace_decode_step` meta is the evidence;
    RLT307/RLT308 absent is the rule-level restatement)."""
    from ray_lightning_tpu.serve.audit import (
        audit_decode_step, trace_decode_step,
    )
    from ray_lightning_tpu.serve.engine import EngineConfig

    # chunk 12 does not divide the 32-token slot (the scheduler's
    # slid-back tail window is exercised on the fused lane too) while
    # still tiling (12 q rows x 2 heads = 24, sublane-aligned; chunk 6
    # would be refused by `paged_prefill_shapes_supported`)
    ecfg = EngineConfig(capacity=4, block_size=8, blocks_per_slot=4,
                        prefill_chunk=12, prefill_batch=2)
    cfg, eng, out_ref, out_fused, mismatched = _fused_leg_harness(
        ecfg, prompt_key=500, param_key=9, rid_prefix="pf", temp=0.6,
        top_k=4, seed_base=91, prompt_floor=2, prompt_mod=7)
    prefill_selected = eng.fused_prefill
    # ONE trace serves all three verdicts: the gather evidence in its
    # meta, the kernel fingerprint, and the audit (fed the same pair
    # via `traced=` — never a second full trace of the same step)
    traced = trace_decode_step(cfg, ecfg, fused=True)
    report = audit_decode_step(cfg, ecfg, topology=topo, fused=True,
                               label="fused smoke prefill step",
                               traced=traced)
    meta = traced[1]
    rules = sorted({f.rule for f in report.findings})
    kernel_in_trace = any("paged_prefill" in k
                          for k in meta["pallas_kernels"])
    leg = {
        "prefill_selected": prefill_selected,
        "stream_mismatches": mismatched,
        "compile_count": eng.compile_count,
        "audit_findings": rules,
        "prefill_kernel_in_trace": kernel_in_trace,
        "dense_paged_gathers": len(meta["dense_paged_gathers"]),
        "prefill_paged_gathers": len(meta["prefill_paged_gathers"]),
        "prefill_path": eng.prefill_path,
    }
    if not prefill_selected:
        failures.append("force_pallas did not select the fused paged-"
                        "prefill path for a kernel-tiling shape")
        return leg
    if mismatched:
        failures.append(
            f"fused-prefill streams diverge from the reference path: "
            f"{mismatched}")
    if eng.compile_count not in (1, -1):
        failures.append(
            f"fused-prefill churn recompiled the step: compile_count="
            f"{eng.compile_count} (want 1)")
    if any(r in ("RLT301", "RLT303", "RLT307", "RLT308")
           for r in rules):
        failures.append(f"fused prefill step audit findings: {rules}")
    if meta["dense_paged_gathers"] or meta["prefill_paged_gathers"]:
        failures.append(
            f"the fused step still materializes a dense paged gather "
            f"(top-level {len(meta['dense_paged_gathers'])}, nested "
            f"{len(meta['prefill_paged_gathers'])}) — the kernels did "
            f"not retire the views")
    if not kernel_in_trace:
        failures.append("the paged-prefill kernel is absent from the "
                        "fused trace — the prefill lane fell back to "
                        "the gathering reference op")
    return leg


def _smoke_prefix_leg(failures: list) -> dict:
    """The prefix-sharing smoke leg: an 8-stream fleet behind ONE
    common system prompt decodes bitwise vs per-stream `generate()`,
    with the shared prefix prefilled exactly once — the cached run's
    prefill-token count must be STRICTLY below the same fleet served
    without the cache, and ``shared_block_fraction`` must be > 0."""
    import jax
    import numpy as np

    from ray_lightning_tpu.models.llama import generate
    from ray_lightning_tpu.serve.engine import DecodeEngine, EngineConfig
    from ray_lightning_tpu.serve.scheduler import Request, Scheduler

    cfg, model, params, _, _ = _tiny_setup(1, 1)
    sys_prompt = np.asarray(jax.random.randint(
        jax.random.key(7), (9,), 0, cfg.vocab_size), np.int32)
    prompts = []
    for i in range(8):
        tail = np.asarray(jax.random.randint(
            jax.random.key(200 + i), (2 + i % 3,), 0, cfg.vocab_size),
            np.int32)
        prompts.append(np.concatenate([sys_prompt, tail]))
    ecfg = EngineConfig(capacity=4, block_size=4, blocks_per_slot=8,
                        prefill_chunk=4)

    def fleet(prefix_cache: bool):
        eng = DecodeEngine(model, params, ecfg)
        eng.warmup()
        sched = Scheduler(eng, prefix_cache=prefix_cache)
        for i, p in enumerate(prompts):
            sched.submit(Request(rid=f"p{i}", prompt=p,
                                 max_new_tokens=6, seed=41 + i))
        outputs = {}
        while sched.busy():
            for comp in sched.tick():
                outputs[comp.rid] = list(comp.tokens)
        return outputs, sched, eng

    outputs, sched, eng = fleet(prefix_cache=True)
    _, sched_cold, _ = fleet(prefix_cache=False)
    bad = []
    for i, p in enumerate(prompts):
        ref = np.asarray(generate(model, params, p[None], 6,
                                  temperature=0.0, seed=41 + i))[0]
        if not np.array_equal(ref, np.asarray(outputs.get(f"p{i}", []))):
            bad.append(f"p{i}")
    leg = {
        "bitwise_mismatches": bad,
        "shared_block_fraction": round(sched.shared_block_fraction, 4),
        "prefill_tokens_issued": sched.prefill_tokens_issued,
        "prefill_tokens_no_sharing": sched_cold.prefill_tokens_issued,
        "compile_count": eng.compile_count,
    }
    if bad:
        failures.append(
            f"prefix-shared streams diverge from generate(): {bad}")
    if sched.shared_block_fraction <= 0.0:
        failures.append(
            "the common system prompt produced no shared blocks "
            f"(shared_block_fraction="
            f"{sched.shared_block_fraction})")
    if not (sched.prefill_tokens_issued
            < sched_cold.prefill_tokens_issued):
        failures.append(
            f"prefix cache did not reduce prefill work: "
            f"{sched.prefill_tokens_issued} issued vs "
            f"{sched_cold.prefill_tokens_issued} without sharing")
    if eng.compile_count not in (1, -1):
        failures.append(
            f"prefix-shared churn recompiled the step: compile_count="
            f"{eng.compile_count} (want 1)")
    return leg


def _smoke_spec_leg(failures: list) -> dict:
    """The speculative smoke leg: draft+target greedy decode must be
    TOKEN-IDENTICAL to plain greedy `generate()` — the accept/reject
    rule is exact, never approximate — still at compile-count 1."""
    import jax
    import numpy as np

    from ray_lightning_tpu.models.llama import Llama, generate
    from ray_lightning_tpu.serve.engine import (
        DecodeEngine, DraftConfig, EngineConfig,
    )
    from ray_lightning_tpu.serve.scheduler import Request, Scheduler

    cfg, model, params, prompts, _ = _tiny_setup(6, 6)
    # an INDEPENDENT draft (same architecture, different weights) —
    # acceptance is partial, so the rejection path runs for real
    draft = Llama(cfg)
    draft_params = jax.jit(draft.init)(jax.random.key(97),
                                       prompts[0])["params"]
    ecfg = EngineConfig(capacity=4, block_size=4, blocks_per_slot=8,
                        prefill_chunk=4, draft=DraftConfig(k=3))
    eng = DecodeEngine(model, params, ecfg, draft_model=draft,
                       draft_params=draft_params)
    eng.warmup()
    sched = Scheduler(eng)
    for i, p in enumerate(prompts):
        sched.submit(Request(rid=f"s{i}", prompt=p[0],
                             max_new_tokens=6, seed=61 + i))
    outputs = {}
    while sched.busy():
        for comp in sched.tick():
            outputs[comp.rid] = list(comp.tokens)
    bad = []
    for i, p in enumerate(prompts):
        ref = np.asarray(generate(model, params, p, 6,
                                  temperature=0.0, seed=61 + i))[0]
        if not np.array_equal(ref, np.asarray(outputs.get(f"s{i}", []))):
            bad.append(f"s{i}")
    leg = {
        "bitwise_mismatches": bad,
        "k": ecfg.draft.k,
        "accepted_tokens_per_step": round(
            sched.accepted_tokens_per_step, 4),
        "compile_count": eng.compile_count,
    }
    if bad:
        failures.append(
            f"speculative greedy decode diverges from plain greedy: "
            f"{bad}")
    if sched.accepted_tokens_per_step < 1.0:
        failures.append(
            f"speculative decode emitted fewer than one token per "
            f"slot-step ({sched.accepted_tokens_per_step}) — the "
            "bonus-token accounting is broken")
    if eng.compile_count not in (1, -1):
        failures.append(
            f"speculative churn recompiled the step: compile_count="
            f"{eng.compile_count} (want 1)")
    return leg


def _run_example(args) -> int:
    import contextlib

    from ray_lightning_tpu.serve.driver import (
        ReplicaGroupConfig, ServeDriver, save_params_npz,
    )
    from ray_lightning_tpu.serve.engine import EngineConfig

    bps = args.blocks_per_slot or 8
    ecfg = EngineConfig(capacity=args.slots, block_size=args.block_size,
                        blocks_per_slot=bps,
                        prefill_chunk=args.prefill_chunk,
                        prefill_batch=args.prefill_batch)
    cfg, model, params, prompts, reqs = _tiny_setup(
        args.requests, args.max_new)
    with contextlib.ExitStack() as stack:
        if args.backend == "process":
            tmp = stack.enter_context(
                tempfile.TemporaryDirectory(prefix="rlt-serve-"))
            pp = os.path.join(tmp, "params.npz")
            save_params_npz(params, pp)
            params_arg = pp
            env = {"JAX_PLATFORMS":
                   os.environ.get("JAX_PLATFORMS", "cpu")}
        else:
            params_arg, env = params, None
        drv = ServeDriver(cfg, params_arg, ReplicaGroupConfig(
            n_replicas=args.replicas, backend=args.backend, engine=ecfg,
            run_dir=args.run_dir, env=env))
        res = drv.run(reqs)
    ttfts = sorted(m["ttft_s"] for m in res.meta.values())
    line = {
        "preset": "example",
        "n_requests": len(reqs),
        "decode_tokens_per_s": round(
            res.stats["decode_tokens_per_s"], 2),
        "slot_occupancy": res.stats.get("slot_occupancy"),
        "ttft_p50_s": round(ttfts[len(ttfts) // 2], 4),
        "ttft_max_s": round(ttfts[-1], 4),
        "compile_count": res.stats.get("compile_count"),
        "restarts": res.restarts,
    }
    if getattr(args, "as_json", False):
        print(json.dumps(line))
    else:
        print(f"served {line['n_requests']} requests: "
              f"{line['decode_tokens_per_s']} tok/s decode, "
              f"occupancy {line['slot_occupancy']:.2f}, "
              f"TTFT p50 {line['ttft_p50_s']}s")
        if args.run_dir:
            print(f"telemetry: {args.run_dir} "
                  f"(python -m ray_lightning_tpu report {args.run_dir})")
    return 0


def _run_flagship(args) -> int:
    """llama3-8b: no weights ship with the repo, so this is the STATIC
    leg — the serve plan + decode-step audit for the flagship config —
    honest about what it is (a box with weights runs `example`-style
    serving through the same driver)."""
    import jax.numpy as jnp

    from ray_lightning_tpu.models.llama import LlamaConfig
    from ray_lightning_tpu.serve.audit import (
        audit_decode_step, format_serve_summary, serve_memory_summary,
    )
    from ray_lightning_tpu.serve.engine import EngineConfig

    cfg = LlamaConfig.llama3_8b(max_seq_len=args.seq_budget,
                                dtype=jnp.bfloat16)
    bps = args.blocks_per_slot or -(-args.seq_budget // args.block_size)
    ecfg = EngineConfig(capacity=args.slots, block_size=args.block_size,
                        blocks_per_slot=bps,
                        prefill_chunk=max(args.prefill_chunk, 128),
                        prefill_batch=args.prefill_batch)
    summary = serve_memory_summary(cfg, ecfg)
    fused = summary["attention_path"] == "paged-pallas"
    report = audit_decode_step(cfg, ecfg, topology=args.topo,
                               label="llama3-8b serve", fused=fused)
    rules = sorted({f.rule for f in report.findings})
    if getattr(args, "as_json", False):
        print(json.dumps({
            "preset": "llama3-8b", "plan": summary,
            "audit": {"findings": rules,
                      "attention_path": summary["attention_path"],
                      "peak_hbm_bytes": report.peak_hbm_bytes,
                      "hbm_budget_bytes": report.hbm_budget_bytes},
        }))
    else:
        print(format_serve_summary(summary))
        print(f"decode-step audit ({args.topo}, "
              f"{summary['attention_path']}): "
              f"{'clean' if not rules else rules}, liveness peak "
              f"{report.peak_hbm_bytes / 1024**3:.2f} GiB")
        print("note: static leg — no weights ship with the repo; with "
              "a params .npz this config serves through the same "
              "driver (docs/SERVING.md)")
    bad = summary["fits"] is False or any(
        r in ("RLT301", "RLT303") for r in rules)
    return 1 if bad else 0


def _run_autotune(args) -> int:
    """``serve <preset> --autotune out.json``: sweep block_size /
    blocks_per_slot for BOTH paged kernels on the preset's shape and
    write the artifact `sweep.apply_autotune` consumes
    (docs/SERVING.md "block-size autotune")."""
    from ray_lightning_tpu.serve.engine import EngineConfig
    from ray_lightning_tpu.serve.sweep import (
        save_artifact, sweep_paged_kernels,
    )

    if args.preset == "llama3-8b":
        import jax.numpy as jnp

        from ray_lightning_tpu.models.llama import LlamaConfig

        cfg = LlamaConfig.llama3_8b(max_seq_len=args.seq_budget,
                                    dtype=jnp.bfloat16)
        bps = args.blocks_per_slot or -(-args.seq_budget
                                        // args.block_size)
        ecfg = EngineConfig(capacity=args.slots,
                            block_size=args.block_size,
                            blocks_per_slot=bps,
                            prefill_chunk=max(args.prefill_chunk, 128),
                            prefill_batch=args.prefill_batch)
    else:
        # the demo sweeps a KERNEL-TILING tiny shape (head_dim 64, GQA
        # 2:1 — the fused smoke leg's config): the main example model's
        # head_dim 16 is refused by both kernels, which would make
        # every candidate fail correctness vacuously
        import jax.numpy as jnp

        from ray_lightning_tpu.models.llama import LlamaConfig

        cfg = LlamaConfig(vocab_size=256, dim=128, n_layers=2,
                          n_heads=2, n_kv_heads=1, hidden_dim=256,
                          max_seq_len=128, remat=False,
                          dtype=jnp.float32)
        ecfg = EngineConfig(capacity=args.slots,
                            block_size=args.block_size,
                            blocks_per_slot=args.blocks_per_slot or 8,
                            prefill_chunk=args.prefill_chunk,
                            prefill_batch=args.prefill_batch)
    artifact = sweep_paged_kernels(cfg, ecfg, topology=args.topo)
    save_artifact(artifact, args.autotune)
    if getattr(args, "as_json", False):
        print(json.dumps(artifact))
    else:
        n_ok = sum(1 for r in artifact["results"]
                   if r["decode"].get("ok") and r["prefill"].get("ok"))
        print(f"swept {len(artifact['results'])} geometries "
              f"({n_ok} passed both kernels' correctness) on backend "
              f"{artifact['backend']}")
        if artifact["winner"]:
            print(f"winner ({artifact['winner_source']}): block_size="
                  f"{artifact['winner']['block_size']} "
                  f"blocks_per_slot="
                  f"{artifact['winner']['blocks_per_slot']} "
                  f"-> {args.autotune}")
        else:
            print(f"no candidate passed correctness -> "
                  f"{args.autotune} (winner: null)")
    return 0 if artifact["winner"] else 1


def run_serve(args) -> int:
    if args.smoke:
        return run_smoke(args)
    if args.autotune:
        return _run_autotune(args)
    if args.preset == "llama3-8b":
        return _run_flagship(args)
    return _run_example(args)
