"""Static analysis of the serving engine: tracecheck the decode step,
price the paged cache in HBM — zero devices, CPU-host safe.

Two consumers:

  * ``plan --serve`` (the serve-aware plan leg): a serving replica's
    HBM story — params + paged pool + the attention path's gathered
    view (the reference lane's capacity-wide dense copy, or the fused
    kernel's surviving per-group prefill gather) + the carried logits
    buffer — against the chip budget, plus the jaxpr-level audit of
    the step itself;
  * the test/format.sh gates: the decode step must audit CLEAN on BOTH
    attention paths — the paged gather/kernel must never read as an
    implicit reshard (RLT301), the step contains no ring collectives to
    deadlock (RLT303), a step that still materializes the dense
    slot-gathered view on a shape the fused kernel supports is flagged
    **RLT307 dense-paged-gather** (fires on the reference-path
    flagship trace; absent on the fused path, where the view does not
    exist; sanctioned on shapes the kernel cannot tile), and a step
    whose cond-nested PREFILL lane still gathers its group-sized pool
    view on a shape the fused prefill kernel tiles is flagged
    **RLT308 dense-paged-prefill-gather** (same fire/sanction
    discipline — the historical blanket sanction of the prefill
    gather became shape-conditional once the kernel covered it).
"""
from __future__ import annotations

from typing import Optional

from ray_lightning_tpu.analysis.costmodel import (
    Topology, paged_decode_traffic_bytes, parse_topology,
)
from ray_lightning_tpu.serve.engine import EngineConfig, build_step
from ray_lightning_tpu.serve.kv_cache import serve_kv_plan_bytes


def _shape_fused_available(model_cfg, engine_cfg: EngineConfig) -> bool:
    """Would the fused DECODE kernel tile this (model, engine) shape on
    a TPU? The PLANNER'S question — shape support only, independent of
    the host's backend (a CPU host planning a v5p deployment must price
    the kernel the TPU will run; the runtime dispatch adds the backend
    gate via `ops.attention.paged_attention_uses_pallas`)."""
    from ray_lightning_tpu.ops.pallas.paged_attention import (
        paged_shapes_supported,
    )

    spec = engine_cfg.pool_spec
    return paged_shapes_supported(
        (engine_cfg.capacity, model_cfg.n_heads, model_cfg.head_dim),
        (spec.n_blocks, spec.block_size, model_cfg.n_kv_heads,
         model_cfg.head_dim))


def _shape_fused_prefill_available(model_cfg,
                                   engine_cfg: EngineConfig) -> bool:
    """The prefill twin of `_shape_fused_available`: would the fused
    PREFILL kernel tile this (model, engine) shape on a TPU? The two
    kernels gate shapes independently (the prefill kernel additionally
    tiles the chunk width)."""
    from ray_lightning_tpu.ops.pallas.paged_prefill import (
        paged_prefill_shapes_supported,
    )

    spec = engine_cfg.pool_spec
    return paged_prefill_shapes_supported(
        (engine_cfg.prefill_batch, engine_cfg.prefill_chunk,
         model_cfg.n_heads, model_cfg.head_dim),
        (spec.n_blocks, spec.block_size, model_cfg.n_kv_heads,
         model_cfg.head_dim))


def trace_decode_step(model_cfg, engine_cfg: EngineConfig,
                      fused: bool = False,
                      fused_prefill: Optional[bool] = None):
    """``(closed_jaxpr, meta)`` for the engine's continuous-batching
    step over abstract inputs — the exact program `DecodeEngine` jits,
    traced with `eval_shape`/`make_jaxpr` so no backend initializes.

    ``fused=True`` traces the fused-lane program — the paged-attention
    kernel is pinned by `build_step`'s baked dispatch decision
    (`PagedDecodeView.use_pallas`, the same static aux `DecodeEngine`
    compiles), so the audited program IS the one a fused replica runs
    regardless of the host's backend; ``fused=False`` traces the
    reference lane as dispatched on this host. ``fused_prefill``
    selects the prefill lane the same way; ``None`` (the default)
    follows ``fused`` GATED BY the prefill kernel's own shape support
    — the engine decides the two lanes independently
    (`DecodeEngine.fused_prefill`), so on a shape only the decode
    kernel tiles the default traces the mixed program the replica
    actually compiles, not a fused-prefill program that would silently
    fall back inside the trace. ``meta`` carries ``pallas_kernels``
    (kernel identities found anywhere in the trace),
    ``dense_paged_gathers`` (top-level capacity-wide gathers of the
    pool — the RLT307 evidence) and ``prefill_paged_gathers``
    (cond-nested group-sized gathers of the pool — the RLT308
    evidence)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_lightning_tpu.models.llama import Llama

    if fused_prefill is None:
        fused_prefill = fused and _shape_fused_prefill_available(
            model_cfg, engine_cfg)
    model = Llama(model_cfg)
    step = build_step(model, engine_cfg, fused=fused,
                      fused_prefill=fused_prefill)
    spec = engine_cfg.pool_spec
    C, CH, B = engine_cfg.capacity, engine_cfg.prefill_chunk, \
        engine_cfg.prefill_batch
    s = jax.ShapeDtypeStruct
    a_tok = np.zeros((1, 2), np.int32)
    a_params = jax.eval_shape(
        lambda k: model.init(k, a_tok)["params"],
        jax.eval_shape(lambda: jax.random.key(0)))
    pool = s((model_cfg.n_layers, spec.n_blocks, spec.block_size,
              model_cfg.n_kv_heads, model_cfg.head_dim),
             jnp.dtype(model_cfg.dtype))
    args = (
        a_params, pool, pool,
        s((C, model_cfg.vocab_size), jnp.float32),       # last_logits
        s((C, spec.blocks_per_slot), jnp.int32),         # tables
        s((C,), jnp.int32), s((C,), jnp.bool_),          # pos, decoding
        s((C,), jnp.float32), s((C,), jnp.int32),        # temp, top_k
        s((C, 2), jnp.uint32),                           # rngs
    )
    if B == 1:
        args += (
            s((), jnp.int32), s((CH,), jnp.int32),       # pf slot/tokens
            s((), jnp.int32), s((), jnp.int32),          # pf pos/last_row
        )
    else:
        args += (
            s((C,), jnp.int32),                          # slot_pad
            s((B,), jnp.int32), s((B, CH), jnp.int32),   # pf slots/tokens
            s((), jnp.int32), s((), jnp.int32),          # pf pos/last_row
            s((B,), jnp.int32),                          # pf pads
        )
    closed = jax.make_jaxpr(step)(*args)
    from ray_lightning_tpu.analysis.tracecheck import _dce

    closed = _dce(closed)
    import jax as _jax

    params_bytes = sum(
        int(np.prod(leaf.shape or (1,))) * leaf.dtype.itemsize
        for leaf in _jax.tree.leaves(a_params))
    pool_shape = tuple(pool.shape)
    return closed, {
        "args": args,
        "params_bytes": params_bytes,
        "fused": fused,
        "fused_prefill": fused_prefill,
        "pallas_kernels": _pallas_kernel_names(closed.jaxpr),
        "dense_paged_gathers": _dense_paged_gathers(
            closed.jaxpr, pool_shape, C),
        "prefill_paged_gathers": _prefill_paged_gathers(
            closed.jaxpr, pool_shape, C,
            engine_cfg.pool_spec.blocks_per_slot),
    }


def _pallas_kernel_names(jaxpr) -> list:
    """Kernel identities anywhere in the trace (recursive) — the
    fingerprint that the fused path actually lowered the kernel. The
    identity string is `tracecheck._pallas_kernel_ident`, the same
    extraction the step auditor records into
    `TraceReport.pallas_kernels`."""
    from ray_lightning_tpu.analysis.tracecheck import _pallas_kernel_ident

    names = []

    def _walk(j):
        for eqn in j.eqns:
            if eqn.primitive.name == "pallas_call":
                names.append(_pallas_kernel_ident(eqn))
            for v in eqn.params.values():
                vals = v if isinstance(v, (list, tuple)) else (v,)
                for x in vals:
                    inner = getattr(x, "jaxpr", None)
                    if inner is not None and hasattr(inner, "eqns"):
                        _walk(inner)

    _walk(jaxpr)
    return names


def _dense_paged_gathers(jaxpr, pool_shape, capacity: int) -> list:
    """TOP-LEVEL gathers of a pool-shaped invar whose output is the
    capacity-wide dense slot view ``[L, C, M, P, Hkv, hd]`` — the
    decode lane's materialized copy, and RLT307's evidence. Top level
    only by design: the prefill lane's per-group gather lives inside
    the step's `lax.cond` and is RLT308's domain
    (`_prefill_paged_gathers` — shape-conditional on the fused PREFILL
    kernel covering it, no longer a blanket sanction; the copy is
    group-sized, priced honestly by `serve_kv_plan_bytes`)."""
    pool_vars = [v for v in jaxpr.invars
                 if tuple(getattr(v.aval, "shape", ())) == pool_shape]
    hits = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name != "gather" or not eqn.invars:
            continue
        if eqn.invars[0] not in pool_vars:
            continue
        out_shape = tuple(getattr(eqn.outvars[0].aval, "shape", ()))
        if (len(out_shape) == 6 and out_shape[0] == pool_shape[0]
                and out_shape[1] == capacity):
            hits.append(out_shape)
    return hits


def _prefill_paged_gathers(jaxpr, pool_shape, capacity: int,
                           blocks_per_slot: int) -> list:
    """Gathers of a pool-shaped operand at ANY nesting level whose
    output is a group-sized dense slot view — the prefill lane's
    materialized per-group copy (it lives inside the step's `lax.cond`)
    and RLT308's evidence. Two shapes qualify:

      * ``[L, B, M, P, Hkv, hd]`` with ``B <= capacity`` and
        ``M == blocks_per_slot`` — the batched lane's group view
        (the capacity-wide B == capacity decode view is RLT307's
        top-level evidence, but nested it is still a dense paged
        gather and counts here);
      * ``[L, M, P, Hkv, hd]`` with ``M == blocks_per_slot`` — the
        single-slot lane's per-row view.

    Matching is by aval shape (a cond/pjit branch's pool invar carries
    the pool's aval), the same discipline as `_dense_paged_gathers`."""
    L, _, P, HKV, HD = pool_shape
    hits = []

    def _match(out_shape) -> bool:
        if len(out_shape) == 6:
            return (out_shape[0] == L and out_shape[1] <= capacity
                    and out_shape[2] == blocks_per_slot
                    and out_shape[3:] == (P, HKV, HD))
        if len(out_shape) == 5:
            return (out_shape[0] == L
                    and out_shape[1] == blocks_per_slot
                    and out_shape[2:] == (P, HKV, HD))
        return False

    def _walk(j, nested):
        for eqn in j.eqns:
            if (nested and eqn.primitive.name == "gather"
                    and eqn.invars
                    and tuple(getattr(eqn.invars[0].aval, "shape", ()))
                    == pool_shape):
                out_shape = tuple(getattr(eqn.outvars[0].aval,
                                          "shape", ()))
                if _match(out_shape):
                    hits.append(out_shape)
            for v in eqn.params.values():
                vals = v if isinstance(v, (list, tuple)) else (v,)
                for x in vals:
                    inner = getattr(x, "jaxpr", None)
                    if inner is not None and hasattr(inner, "eqns"):
                        _walk(inner, True)

    _walk(jaxpr, False)
    return hits


def _tp_invar_seeds(model_cfg, meta, tp: int):
    """`_VarInfo` seeds for the step's invars under a ``tp``-way tensor
    mesh — the SAME layout `DecodeEngine` places, so the audited
    collectives are the served ones: params via
    `engine.serving_param_specs` (wqkv/gate_up column-split, wo/w_down
    row-split, embeddings vocab-split), the two pool leaves KV-head
    sharded (`kv_cache.pool_partition_spec`), every host-fed input and
    the carried logits replicated (the scheduler is tp-oblivious)."""
    import dataclasses as _dc

    import jax

    from ray_lightning_tpu.analysis.tracecheck import (
        _repl, _spec_of_partition_spec, _VarInfo,
    )
    from ray_lightning_tpu.models.llama import Llama
    from ray_lightning_tpu.parallel.mesh import MeshSpec
    from ray_lightning_tpu.serve.engine import serving_param_specs
    from ray_lightning_tpu.serve.kv_cache import (
        pool_partition_spec, validate_pool_tp,
    )

    validate_pool_tp(model_cfg, tp)
    live = {"tensor"}

    def canon(spec_t):
        return tuple(frozenset(ax for ax in s if ax in live)
                     for s in spec_t)

    axis_names = tuple(f.name for f in _dc.fields(MeshSpec))
    a_params = meta["args"][0]
    model = Llama(model_cfg)
    seeds = []
    # shape->spec matcher for vars the walk re-derives structurally —
    # scan-SLICED per-layer weights chiefly (audit_step's discipline:
    # a stacked [L, ...] leaf also registers its per-trip suffix)
    param_shapes = {}
    for (path, spec), leaf in zip(
            serving_param_specs(model, a_params, axis_names),
            jax.tree.leaves(a_params)):
        shape = tuple(getattr(leaf, "shape", ()))
        cspec = canon(_spec_of_partition_spec(spec, len(shape)))
        seeds.append(_VarInfo(cspec, param=True, path=f"params/{path}"))
        param_shapes.setdefault(shape, (cspec, f"params/{path}"))
        if len(shape) >= 2:
            param_shapes.setdefault(shape[1:],
                                    (cspec[1:], f"params/{path}"))
    pool_spec = canon(_spec_of_partition_spec(pool_partition_spec(tp), 5))
    for i, arg in enumerate(meta["args"][1:], start=1):
        ndim = len(getattr(arg, "shape", ()))
        if i in (1, 2):
            seeds.append(_VarInfo(
                pool_spec, param=True,
                path="pool_k" if i == 1 else "pool_v"))
        else:
            seeds.append(_VarInfo(_repl(ndim), param=True))
    return seeds, param_shapes


def audit_decode_step(model_cfg, engine_cfg: EngineConfig,
                      topology="v5p-8", reserve_fraction: float = 0.10,
                      label: str = "serve decode step",
                      fused: bool = False,
                      fused_prefill: Optional[bool] = None,
                      traced=None, numerics: bool = True,
                      tp: int = 1):
    """Full tracecheck walk of the decode step: collective schedule
    (none expected on a single-replica tp=1 step — each replica is one
    model copy), RLT301/303/307/308 findings, and the liveness HBM peak
    vs the chip budget. Returns a `tracecheck.TraceReport`.

    ``tp > 1`` audits ONE RANK of a tensor-parallel replica: the
    invars are seeded with the engine's served layout
    (`_tp_invar_seeds`) and the walk prices the decode step's implicit
    collectives — the per-tick attention/MLP psums over the ``tensor``
    axis — exactly the way training steps are priced (wire bytes on
    ICI; ``sum(ev.wire_bytes for ev in report.collectives)`` is the
    decode ICI bytes/tick the bench gate ratchets). The traced program
    is identical (SPMD comes from shardings at jit time), so ``traced``
    reuse stays valid across ``tp`` values.

    ``numerics`` additionally runs numcheck's RLT801-805 pass over the
    same jaxpr (the int8-KV campaign's audit surface: an unscaled int8
    pool read fires RLT805 right here) and fills the report's
    ``precision`` ledger — per-dtype params / KV-pool / activation
    bytes; the decode step has no loss output, so the widest-path entry
    stays None.

    RLT307 (dense-paged-gather) fires when the traced step materializes
    the capacity-wide dense KV view although the fused decode kernel
    tiles the shape — i.e. on the reference-path flagship trace. RLT308
    (dense-paged-prefill-gather) is the prefill twin: it fires when the
    cond-nested prefill lane still gathers its group-sized pool view
    although the fused PREFILL kernel tiles the shape (the historical
    blanket sanction of the prefill gather became shape-conditional
    once the kernel covered it). The fused trace has neither gather
    (the views never exist), and shapes the kernels cannot tile are
    sanctioned.

    ``traced`` takes a ``(closed, meta)`` pair from an earlier
    `trace_decode_step` call with the SAME config/lanes so a caller
    that already holds the trace (the smoke legs read meta's gather
    evidence directly) never pays a second full trace of the same
    step — the PR 11 one-trace discipline."""
    from ray_lightning_tpu.analysis.findings import Finding
    from ray_lightning_tpu.analysis.tracecheck import (
        TraceReport, _repl, _StepAuditor, _VarInfo, classify_overlap,
    )

    topo = (topology if isinstance(topology, Topology)
            else parse_topology(topology))
    closed, meta = (traced if traced is not None
                    else trace_decode_step(model_cfg, engine_cfg,
                                           fused=fused,
                                           fused_prefill=fused_prefill))
    seeds, param_shapes = (_tp_invar_seeds(model_cfg, meta, tp)
                           if tp > 1 else (None, {}))
    auditor = _StepAuditor({"tensor": tp} if tp > 1 else {}, topo,
                           param_shapes)
    jaxpr = closed.jaxpr
    env = {}
    if seeds is not None:
        n = min(len(jaxpr.invars), len(seeds))
        for v, s in zip(jaxpr.invars[:n], seeds[:n]):
            env[v] = s
        for v in jaxpr.invars[n:]:
            env[v] = _VarInfo(
                _repl(len(getattr(v.aval, "shape", ()))), param=True)
    else:
        for v in jaxpr.invars:
            env[v] = _VarInfo(
                _repl(len(getattr(v.aval, "shape", ()))), param=True)
    for v in jaxpr.constvars:
        env[v] = _VarInfo(_repl(len(getattr(v.aval, "shape", ()))),
                          param=True)
    peak, peak_by = auditor.walk(jaxpr, env, 1, False)
    if tp > 1:
        # the engine's jit pins every non-pool output REPLICATED at the
        # boundary (DecodeEngine out_shardings): the column-split
        # lm_head leaves `last_logits` vocab-sharded, so GSPMD
        # all-gathers it over `tensor` at the step's edge — the
        # dominant decode collective by bytes, and invisible inside the
        # traced function (the constraint lives in jit metadata, not
        # the jaxpr). Priced here from the walked output specs: the
        # pools (outvars 0-1) keep their sharding, everything else
        # gathers whatever tensor axes survive to the boundary.
        for i, v in enumerate(jaxpr.outvars):
            if i < 2:
                continue
            spec = auditor._info(v, env).spec
            if not spec:
                continue
            lost = {ax for s in spec for ax in s}
            if lost:
                auditor.record(
                    "all_gather", auditor._aval_bytes(v.aval, None),
                    sorted(lost), 1, implicit=True,
                    source="jit boundary (replicated out_shardings)",
                    dtype=str(getattr(v.aval, "dtype", "")) or None)
    findings = auditor.findings
    budget = int(topo.hbm_bytes * (1 - reserve_fraction))
    gib = 1024**3
    if peak > budget:
        findings.append(Finding(
            "RLT302",
            f"estimated peak HBM {peak / gib:.2f} GiB/device exceeds "
            f"the {topo.device_kind} budget {budget / gib:.2f} GiB: the "
            "serving step will OOM on this chip — shrink capacity, "
            "blocks_per_slot, or the pool",
            symbol=label))
    import math

    import numpy as np

    def _view_gib(shape) -> float:
        # k + v gathers at the POOL's dtype (model_cfg.dtype — the
        # first step invar is a param leaf whose dtype can differ,
        # e.g. f32 params serving a bf16 cache)
        return (2 * math.prod(shape)
                * np.dtype(model_cfg.dtype).itemsize) / gib

    if meta["dense_paged_gathers"] and _shape_fused_available(
            model_cfg, engine_cfg):
        shape = meta["dense_paged_gathers"][0]
        findings.append(Finding(
            "RLT307",
            f"the decode lane gathers a dense {list(shape)} slot view "
            f"of the paged pool every tick (~{_view_gib(shape):.2f} "
            "GiB of HBM + a full copy of traffic) on a shape the fused "
            "paged-attention kernel tiles — the kernel consumes the "
            "pool through the block tables and retires the view "
            "(selected automatically on TPU; "
            "docs/SERVING.md 'paged-attention kernel')",
            symbol=label))
    if meta["prefill_paged_gathers"] and _shape_fused_prefill_available(
            model_cfg, engine_cfg):
        shape = meta["prefill_paged_gathers"][0]
        findings.append(Finding(
            "RLT308",
            f"the prefill lane gathers a dense {list(shape)} "
            "group-sized view of the paged pool every chunk "
            f"(~{_view_gib(shape):.2f} GiB of HBM + a per-chunk copy "
            "of traffic) on a shape the fused paged-prefill kernel "
            "tiles — the kernel attends causally through the block "
            "tables and retires the last dense gather (selected "
            "automatically on TPU; docs/SERVING.md 'paged prefill "
            "kernel')",
            symbol=label))
    overlap = classify_overlap(auditor.events, auditor.scopes, topo,
                               scheduled=auditor.saw_prefetch_marker)
    precision = None
    if numerics:
        import jax as _jax

        from ray_lightning_tpu.analysis import numcheck as _numcheck

        findings.extend(_numcheck.numcheck_jaxpr(closed)[0])
        # the serve ledger's classes: params, the paged KV pool (args
        # 1-2: the k/v pools — the bytes the int8-KV campaign will
        # shrink), and whatever else the liveness peak holds. tp > 1:
        # per-SHARD bytes via the seeded specs (same division the
        # liveness walk applied)
        p_leaves = _jax.tree.leaves(meta["args"][0])
        params_by: dict = {}
        for i, leaf in enumerate(p_leaves):
            dt = str(leaf.dtype)
            b = (auditor._aval_bytes(leaf, seeds[i].spec)
                 if seeds is not None else
                 int(np.prod(leaf.shape or (1,))) * leaf.dtype.itemsize)
            params_by[dt] = params_by.get(dt, 0) + b
        pool_by: dict = {}
        for pl in meta["args"][1:3]:
            dt = str(pl.dtype)
            pool_by[dt] = pool_by.get(dt, 0) + int(
                np.prod(pl.shape)) * pl.dtype.itemsize // tp
        act_by: dict = {}
        for dt, b in peak_by.items():
            rem = b - params_by.get(dt, 0) - pool_by.get(dt, 0)
            if rem > 0:
                act_by[dt] = rem
        precision = {
            "params": params_by,
            "opt_state": {},
            "activations": act_by,
            "kv_pool": pool_by,
            "loss_widest_dtype": None,
        }
    params_dev = meta["params_bytes"]
    if seeds is not None:
        import jax as _jax2

        params_dev = sum(
            auditor._aval_bytes(leaf, s.spec)
            for leaf, s in zip(_jax2.tree.leaves(meta["args"][0]),
                               seeds))
    return TraceReport(
        topology=topo,
        mesh_axes={"tensor": tp} if tp > 1 else {},
        collectives=auditor.events,
        overlap=overlap,
        findings=findings,
        params_bytes_per_device=params_dev,
        opt_bytes_per_device=0,
        peak_hbm_bytes=peak,
        hbm_budget_bytes=budget,
        label=label,
        pallas_kernels=auditor.pallas_kernels,
        precision=precision,
    )


def serve_memory_summary(model_cfg, engine_cfg: EngineConfig,
                         device_kind: str = "TPU v5p",
                         hbm_bytes: Optional[int] = None,
                         fused: Optional[bool] = None,
                         fused_prefill: Optional[bool] = None,
                         tp: int = 1) -> dict:
    """The serve-aware plan leg: itemized replica HBM (no optimizer —
    serving holds weights, the paged pool, the attention paths'
    surviving gathered view, and the carried logits) with a fits
    verdict against the chip budget. Pure byte math + one eval_shape;
    no devices.

    ``fused=None`` / ``fused_prefill=None`` auto-select by SHAPE
    support (the planner prices the paths the TPU deployment will run
    — `_shape_fused_available` / `_shape_fused_prefill_available`);
    pass False/True to price a specific path (the before/after table
    in docs/SERVING.md is exactly these pairs).

    ``tp > 1`` prices ONE RANK of a tensor-parallel replica (the
    ``plan --serve --tp N`` leg): params divide by ``tp`` exactly where
    the engine's layout shards them (`engine.serving_param_specs` —
    replicated leaves like norm gains stay whole), the pool and every
    KV view carry the head axis and divide, and the carried logits
    stay replicated. The fits verdict is per-chip."""
    import dataclasses as _dc

    import jax
    import numpy as np

    from ray_lightning_tpu.analysis.costmodel import (
        paged_prefill_traffic_bytes,
    )
    from ray_lightning_tpu.models.llama import Llama
    from ray_lightning_tpu.parallel.plan import hbm_bytes_for_kind
    from ray_lightning_tpu.serve.kv_cache import gathered_view_bytes

    if fused is None:
        fused = _shape_fused_available(model_cfg, engine_cfg)
    if fused_prefill is None:
        fused_prefill = _shape_fused_prefill_available(model_cfg,
                                                       engine_cfg)
    model = Llama(model_cfg)
    a_params = jax.eval_shape(
        lambda k: model.init(k, np.zeros((1, 2), np.int32))["params"],
        jax.eval_shape(lambda: jax.random.key(0)))
    if tp > 1:
        from ray_lightning_tpu.parallel.mesh import MeshSpec
        from ray_lightning_tpu.serve.engine import serving_param_specs

        axis_names = tuple(f.name for f in _dc.fields(MeshSpec))
        params_bytes = 0
        for (_, pspec), leaf in zip(
                serving_param_specs(model, a_params, axis_names),
                jax.tree.leaves(a_params)):
            b = int(np.prod(leaf.shape or (1,))) * leaf.dtype.itemsize
            if any("tensor" in ((e,) if isinstance(e, str) else tuple(e))
                   for e in tuple(pspec) if e is not None):
                b //= tp
            params_bytes += b
    else:
        params_bytes = sum(
            int(np.prod(leaf.shape or (1,))) * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(a_params))
    spec = engine_cfg.pool_spec
    kv = serve_kv_plan_bytes(model_cfg, spec, engine_cfg.capacity,
                             fused=fused,
                             prefill_batch=engine_cfg.prefill_batch,
                             fused_prefill=fused_prefill, tp=tp)
    budget = hbm_bytes if hbm_bytes is not None else \
        hbm_bytes_for_kind(device_kind)
    usable = int(budget * 0.90)
    # the retired term is REPORTING (what the kernels bought back) and
    # prefill_gather_bytes is an ITEMIZATION of the surviving view (a
    # slice of gathered_view_bytes, never an extra buffer) — neither
    # may inflate the fits verdict
    resident = {k: v for k, v in kv.items()
                if k not in ("gathered_view_retired_bytes",
                             "prefill_gather_bytes")}
    total = params_bytes + sum(resident.values())
    # per-chunk prefill traffic: the group's span (block reads) + the
    # chunk's new K/V write, with the reference lane's view write+read
    # on top (costmodel.paged_prefill_traffic_bytes)
    group_span = int(gathered_view_bytes(
        model_cfg, spec, min(engine_cfg.prefill_batch,
                             engine_cfg.capacity))) // tp
    itemsize = np.dtype(model_cfg.dtype).itemsize
    chunk_bytes = (2 * model_cfg.n_layers * engine_cfg.prefill_batch
                   * engine_cfg.prefill_chunk * model_cfg.n_kv_heads
                   * model_cfg.head_dim * itemsize) // tp
    return {
        "params_bytes": int(params_bytes),
        **kv,
        "tp": tp,
        "attention_path": ("paged-pallas" if fused
                           else "reference-gather"),
        "prefill_attention_path": ("paged-pallas" if fused_prefill
                                   else "reference-gather"),
        "decode_kv_traffic_bytes_per_tick": paged_decode_traffic_bytes(
            kv["pool_bytes"], serve_kv_plan_bytes(
                model_cfg, spec, engine_cfg.capacity,
                fused=False, tp=tp)["gathered_view_bytes"], fused),
        "prefill_kv_traffic_bytes_per_chunk":
            paged_prefill_traffic_bytes(group_span, chunk_bytes,
                                        fused_prefill),
        "capacity": engine_cfg.capacity,
        "block_size": spec.block_size,
        "n_blocks": spec.n_blocks,
        "max_slot_len": engine_cfg.max_slot_len,
        "per_device_bytes": int(total),
        "budget_bytes": usable,
        "fits": total <= usable,
    }


def _param_count(model_cfg) -> int:
    """Parameter count by eval_shape — no device, no init."""
    import jax
    import numpy as np

    from ray_lightning_tpu.models.llama import Llama

    model = Llama(model_cfg)
    a_params = jax.eval_shape(
        lambda key: model.init(key, np.zeros((1, 2), np.int32))["params"],
        jax.eval_shape(lambda: jax.random.key(0)))
    return sum(int(np.prod(leaf.shape or (1,)))
               for leaf in jax.tree.leaves(a_params))


def speculative_plan(model_cfg, draft_cfg, engine_cfg: EngineConfig,
                     accept_rate: float = 0.6) -> dict:
    """Price speculative decoding at this (target, draft, engine)
    shape — pure byte/FLOP math, no devices (the ``plan --serve`` and
    bench static-pricing leg).

    The cost model: one speculative tick spends ONE k-wide verify pass
    of the target (k token-forwards of compute, but a SINGLE sweep of
    the weights + pool — the memory-bound decode's actual currency)
    plus ``k`` single-token draft trips, and emits ``1 +
    accept_rate * (k - 1)`` tokens in expectation. Against ``k`` plain
    decode ticks (k weight+pool sweeps for k tokens), the win is the
    HBM-traffic ratio ``memory_bound_speedup_x``; the FLOP overhead
    ``flops_overhead_x`` is the price (verify recomputes every
    proposal, and rejected tails are discarded work)."""
    import numpy as np

    from ray_lightning_tpu.serve.kv_cache import pool_bytes

    k = engine_cfg.draft.k if engine_cfg.draft is not None else 4
    if not 0.0 <= accept_rate <= 1.0:
        raise ValueError(f"accept_rate {accept_rate} not in [0, 1]")
    n_t, n_d = _param_count(model_cfg), _param_count(draft_cfg)
    spec = engine_cfg.pool_spec
    flops_per_token = 2 * n_t                 # one target token-forward
    verify_step_flops = k * flops_per_token   # one k-wide chunk
    draft_flops_per_tick = k * 2 * n_d        # k single-token trips
    expected = 1.0 + accept_rate * (k - 1)
    params_bytes = n_t * np.dtype(model_cfg.dtype).itemsize
    draft_params_bytes = n_d * np.dtype(draft_cfg.dtype).itemsize
    pool = pool_bytes(model_cfg, spec)
    draft_pool = pool_bytes(draft_cfg, spec)
    # HBM read traffic per tick: the base tick sweeps target weights +
    # pool once per token; the spec tick sweeps them once per k-token
    # verify, plus k draft sweeps
    base_reads = params_bytes + pool
    spec_reads = base_reads + k * (draft_params_bytes + draft_pool)
    return {
        "k": k,
        "accept_rate": accept_rate,
        "target_params": n_t,
        "draft_params": n_d,
        "draft_params_bytes": int(draft_params_bytes),
        "draft_pool_bytes": int(draft_pool),
        "verify_step_flops": int(verify_step_flops),
        "draft_flops_per_tick": int(draft_flops_per_tick),
        "base_decode_flops_per_token": int(flops_per_token),
        "expected_tokens_per_tick": expected,
        "flops_per_emitted_token": int(
            (verify_step_flops + draft_flops_per_tick) / expected),
        "flops_overhead_x": (verify_step_flops + draft_flops_per_tick)
        / (expected * flops_per_token),
        "hbm_read_bytes_per_tick_base": int(base_reads),
        "hbm_read_bytes_per_tick_spec": int(spec_reads),
        "memory_bound_speedup_x": expected * base_reads / spec_reads,
    }


def shared_prefix_plan(model_cfg, engine_cfg: EngineConfig,
                       n_streams: int = 8,
                       prefix_tokens: Optional[int] = None) -> dict:
    """Price prefix sharing for ``n_streams`` requests over a common
    ``prefix_tokens``-token prompt prefix (default: half the slot).
    Only FULL blocks share (K/V at a position depends on the whole
    prefix, so the chain caches per complete block); the savings are
    the pool bytes and prefill tokens the other ``n_streams - 1``
    requests never spend — the ``plan --serve`` / bench static-pricing
    twin of the scheduler's measured `shared_block_fraction`."""
    import numpy as np

    spec = engine_cfg.pool_spec
    P = spec.block_size
    if prefix_tokens is None:
        prefix_tokens = engine_cfg.max_slot_len // 2
    if n_streams < 1:
        raise ValueError(f"n_streams {n_streams} < 1")
    full = min(prefix_tokens, engine_cfg.max_slot_len) // P
    block_bytes = (2 * model_cfg.n_layers * P * model_cfg.n_kv_heads
                   * model_cfg.head_dim
                   * np.dtype(model_cfg.dtype).itemsize)
    return {
        "n_streams": n_streams,
        "prefix_tokens": int(prefix_tokens),
        "shared_full_blocks": int(full),
        "block_bytes": int(block_bytes),
        "pool_bytes_without_sharing": int(n_streams * full * block_bytes),
        "pool_bytes_with_sharing": int(full * block_bytes),
        "shared_pool_bytes_saved": int(
            (n_streams - 1) * full * block_bytes),
        "prefill_tokens_saved": int((n_streams - 1) * full * P),
    }


def format_serve_summary(s: dict) -> str:
    gib = 1024**3
    fused = s.get("attention_path") == "paged-pallas"
    fused_pf = s.get("prefill_attention_path") == "paged-pallas"
    if fused and fused_pf:
        view_line = (
            f"  gathered view    {s['gathered_view_bytes'] / gib:7.2f} "
            "GiB  (prefill gather itemized at "
            f"{s.get('prefill_gather_bytes', 0) / gib:.2f} GiB; the "
            f"{s['gathered_view_retired_bytes'] / gib:.2f} GiB dense "
            "views are RETIRED by the fused paged decode + prefill "
            "kernels — no dense gather remains)")
    elif fused:
        view_line = (
            f"  prefill gather   {s['gathered_view_bytes'] / gib:7.2f} "
            "GiB  (per-group prefill copy; the decode lane's "
            f"{s['gathered_view_retired_bytes'] / gib:.2f} GiB dense "
            "view is RETIRED by the fused paged-attention kernel, and "
            "the fused paged-prefill kernel retires this remainder)")
    else:
        view_line = (
            f"  gathered view    {s['gathered_view_bytes'] / gib:7.2f} "
            "GiB  (reference engine's dense copy; the fused paged "
            "decode + prefill kernels retire it)")
    traffic_tail = ")" if fused else " + dense-view write+read)"
    pf_traffic = s.get("prefill_kv_traffic_bytes_per_chunk")
    tp = s.get("tp", 1)
    tp_tag = (f", tp={tp} (per-shard bytes, one rank of the replica "
              "group)" if tp > 1 else "")
    lines = [
        f"serve plan: {s['capacity']} slots x {s['max_slot_len']} "
        f"tokens, pool {s['n_blocks']} x {s['block_size']}-token "
        f"blocks, attention path: {s.get('attention_path', '?')}, "
        f"prefill path: {s.get('prefill_attention_path', '?')}"
        + tp_tag,
        f"  params           {s['params_bytes'] / gib:7.2f} GiB",
        f"  kv pool          {s['pool_bytes'] / gib:7.2f} GiB",
        view_line,
        f"  carried logits   {s['last_logits_bytes'] / gib:7.2f} GiB",
        f"  decode KV traffic {s['decode_kv_traffic_bytes_per_tick'] / gib:6.2f}"
        " GiB/tick (cost model: pool read" + traffic_tail,
    ]
    if pf_traffic is not None:
        lines.append(
            f"  prefill KV traffic {pf_traffic / gib:5.2f} GiB/chunk "
            "(cost model: group-block reads + chunk write"
            + (")" if fused_pf else " + group-view write+read)"))
    lines.append(
        f"  total {s['per_device_bytes'] / gib:.2f} GiB vs budget "
        f"{s['budget_bytes'] / gib:.2f} GiB — "
        f"{'fits' if s['fits'] else 'DOES NOT FIT'}")
    return "\n".join(lines)
