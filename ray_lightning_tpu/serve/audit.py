"""Static analysis of the serving engine: tracecheck the decode step,
price the paged cache in HBM — zero devices, CPU-host safe.

Two consumers:

  * ``plan --serve`` (the serve-aware plan leg): a serving replica's
    HBM story — params + paged pool + the dense gathered view the
    reference step materializes + the carried logits buffer — against
    the chip budget, plus the jaxpr-level audit of the step itself;
  * the test/format.sh gates: the decode step must audit CLEAN — the
    paged-attention gather is an explicit, position-masked table lookup
    and must never read as an implicit reshard (RLT301), and the step
    contains no ring collectives to deadlock (RLT303).
"""
from __future__ import annotations

from typing import Optional

from ray_lightning_tpu.analysis.costmodel import Topology, parse_topology
from ray_lightning_tpu.serve.engine import EngineConfig, build_step
from ray_lightning_tpu.serve.kv_cache import serve_kv_plan_bytes


def trace_decode_step(model_cfg, engine_cfg: EngineConfig):
    """``(closed_jaxpr, meta)`` for the engine's continuous-batching
    step over abstract inputs — the exact program `DecodeEngine` jits,
    traced with `eval_shape`/`make_jaxpr` so no backend initializes."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_lightning_tpu.models.llama import Llama

    model = Llama(model_cfg)
    step = build_step(model, engine_cfg)
    spec = engine_cfg.pool_spec
    C, CH = engine_cfg.capacity, engine_cfg.prefill_chunk
    s = jax.ShapeDtypeStruct
    a_tok = np.zeros((1, 2), np.int32)
    a_params = jax.eval_shape(
        lambda k: model.init(k, a_tok)["params"],
        jax.eval_shape(lambda: jax.random.key(0)))
    pool = s((model_cfg.n_layers, spec.n_blocks, spec.block_size,
              model_cfg.n_kv_heads, model_cfg.head_dim),
             jnp.dtype(model_cfg.dtype))
    args = (
        a_params, pool, pool,
        s((C, model_cfg.vocab_size), jnp.float32),       # last_logits
        s((C, spec.blocks_per_slot), jnp.int32),         # tables
        s((C,), jnp.int32), s((C,), jnp.bool_),          # pos, decoding
        s((C,), jnp.float32), s((C,), jnp.int32),        # temp, top_k
        s((C, 2), jnp.uint32),                           # rngs
        s((), jnp.int32), s((CH,), jnp.int32),           # pf slot/tokens
        s((), jnp.int32), s((), jnp.int32),              # pf pos/last_row
    )
    closed = jax.make_jaxpr(step)(*args)
    from ray_lightning_tpu.analysis.tracecheck import _dce

    closed = _dce(closed)
    import jax as _jax

    params_bytes = sum(
        int(np.prod(leaf.shape or (1,))) * leaf.dtype.itemsize
        for leaf in _jax.tree.leaves(a_params))
    return closed, {"args": args, "params_bytes": params_bytes}


def audit_decode_step(model_cfg, engine_cfg: EngineConfig,
                      topology="v5p-8", reserve_fraction: float = 0.10,
                      label: str = "serve decode step"):
    """Full tracecheck walk of the decode step: collective schedule
    (none expected on a single-replica step — each replica is one model
    copy), RLT301/303 findings, and the liveness HBM peak vs the chip
    budget. Returns a `tracecheck.TraceReport`."""
    from ray_lightning_tpu.analysis.tracecheck import (
        Finding, TraceReport, _repl, _StepAuditor, _VarInfo,
        classify_overlap,
    )

    topo = (topology if isinstance(topology, Topology)
            else parse_topology(topology))
    closed, meta = trace_decode_step(model_cfg, engine_cfg)
    auditor = _StepAuditor({}, topo, {})
    jaxpr = closed.jaxpr
    env = {}
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        env[v] = _VarInfo(_repl(len(getattr(v.aval, "shape", ()))),
                          param=True)
    peak = auditor.walk(jaxpr, env, 1, False)
    findings = auditor.findings
    budget = int(topo.hbm_bytes * (1 - reserve_fraction))
    if peak > budget:
        gib = 1024**3
        findings.append(Finding(
            "RLT302",
            f"estimated peak HBM {peak / gib:.2f} GiB/device exceeds "
            f"the {topo.device_kind} budget {budget / gib:.2f} GiB: the "
            "serving step will OOM on this chip — shrink capacity, "
            "blocks_per_slot, or the pool",
            symbol=label))
    overlap = classify_overlap(auditor.events, auditor.scopes, topo,
                               scheduled=auditor.saw_prefetch_marker)
    return TraceReport(
        topology=topo,
        mesh_axes={},
        collectives=auditor.events,
        overlap=overlap,
        findings=findings,
        params_bytes_per_device=meta["params_bytes"],
        opt_bytes_per_device=0,
        peak_hbm_bytes=peak,
        hbm_budget_bytes=budget,
        label=label,
    )


def serve_memory_summary(model_cfg, engine_cfg: EngineConfig,
                         device_kind: str = "TPU v5p",
                         hbm_bytes: Optional[int] = None) -> dict:
    """The serve-aware plan leg: itemized replica HBM (no optimizer —
    serving holds weights, the paged pool, the step's dense gathered
    view, and the carried logits) with a fits verdict against the chip
    budget. Pure byte math + one eval_shape; no devices."""
    import jax
    import numpy as np

    from ray_lightning_tpu.models.llama import Llama
    from ray_lightning_tpu.parallel.plan import hbm_bytes_for_kind

    model = Llama(model_cfg)
    a_params = jax.eval_shape(
        lambda k: model.init(k, np.zeros((1, 2), np.int32))["params"],
        jax.eval_shape(lambda: jax.random.key(0)))
    params_bytes = sum(
        int(np.prod(leaf.shape or (1,))) * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(a_params))
    spec = engine_cfg.pool_spec
    kv = serve_kv_plan_bytes(model_cfg, spec, engine_cfg.capacity)
    budget = hbm_bytes if hbm_bytes is not None else \
        hbm_bytes_for_kind(device_kind)
    usable = int(budget * 0.90)
    total = params_bytes + sum(kv.values())
    return {
        "params_bytes": int(params_bytes),
        **kv,
        "capacity": engine_cfg.capacity,
        "block_size": spec.block_size,
        "n_blocks": spec.n_blocks,
        "max_slot_len": engine_cfg.max_slot_len,
        "per_device_bytes": int(total),
        "budget_bytes": usable,
        "fits": total <= usable,
    }


def format_serve_summary(s: dict) -> str:
    gib = 1024**3
    lines = [
        f"serve plan: {s['capacity']} slots x {s['max_slot_len']} "
        f"tokens, pool {s['n_blocks']} x {s['block_size']}-token blocks",
        f"  params           {s['params_bytes'] / gib:7.2f} GiB",
        f"  kv pool          {s['pool_bytes'] / gib:7.2f} GiB",
        f"  gathered view    {s['gathered_view_bytes'] / gib:7.2f} GiB"
        "  (reference engine's dense copy; a fused paged-attention "
        "kernel retires it)",
        f"  carried logits   {s['last_logits_bytes'] / gib:7.2f} GiB",
        f"  total {s['per_device_bytes'] / gib:.2f} GiB vs budget "
        f"{s['budget_bytes'] / gib:.2f} GiB — "
        f"{'fits' if s['fits'] else 'DOES NOT FIT'}",
    ]
    return "\n".join(lines)
