"""`python -m ray_lightning_tpu lint` — the shardcheck CLI.

Sibling of the doctor/plan subcommands (`__main__.py`): zero hardware,
runs anywhere Python runs. Targets are files, directories (recursed), or
importable dotted module names (resolved to their source, never
executed beyond the import machinery's parent-package resolution).

Exit status: 0 clean (no finding at/above --fail-on), 1 findings at or
above the gate, 2 invalid invocation (missing path, unresolvable
module). With --json the report is ONE machine-readable JSON object.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from ray_lightning_tpu.analysis.findings import (
    RULES, SEVERITY_RANK, Finding, meets,
)
from ray_lightning_tpu.analysis.linter import iter_python_files, lint_paths


def add_lint_parser(sub) -> None:
    """Attach the `lint` subparser (argparse) to `sub`."""
    p = sub.add_parser(
        "lint",
        help="static-analyze modules for sharding-plan and traced-code "
             "antipatterns (no TPU, no target imports)")
    p.add_argument(
        "targets", nargs="*", default=None,
        help="files, directories, or dotted module names (default: the "
             "installed ray_lightning_tpu package)")
    p.add_argument(
        "--severity", choices=("note", "warning", "error"), default="note",
        help="minimum severity to report (default: note — everything)")
    p.add_argument(
        "--fail-on", choices=("note", "warning", "error"), default="error",
        help="exit 1 when any finding is at/above this severity "
             "(default: error)")
    p.add_argument(
        "--disable", default="",
        help="comma-separated rule ids to drop entirely (e.g. RLT204)")
    p.add_argument(
        "--mesh-axes", default="",
        help="comma-separated EXTRA mesh-axis names to accept in "
             "PartitionSpec literals beyond the canonical six")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    # same namespace-sharing contract as the plan subparser: a plain
    # default would clobber a `--json` given before the subcommand
    p.add_argument("--json", action="store_true", dest="as_json",
                   default=argparse.SUPPRESS)


def _resolve_target(target: str) -> Optional[str]:
    """A path stays a path; a dotted name resolves to its source file
    (or package directory)."""
    if os.path.exists(target):
        return target
    if os.sep in target or target.endswith(".py"):
        return None
    import importlib.util

    try:
        spec = importlib.util.find_spec(target)
    except (ImportError, ValueError, ModuleNotFoundError):
        return None
    if spec is None:
        return None
    if spec.submodule_search_locations:
        return list(spec.submodule_search_locations)[0]
    return spec.origin


def run_lint(args) -> int:
    as_json = getattr(args, "as_json", False)
    if args.list_rules:
        if as_json:
            print(json.dumps({rid: {
                "name": r.name, "severity": r.severity,
                "summary": r.summary} for rid, r in sorted(RULES.items())}))
        else:
            for rid, r in sorted(RULES.items()):
                print(f"{rid}  {r.severity:<8} {r.name}: {r.summary}")
        return 0

    targets = args.targets or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]
    resolved: List[str] = []
    for t in targets:
        r = _resolve_target(t)
        if r is None:
            msg = (f"no such file, directory, or importable module: "
                   f"{t!r}")
            if as_json:
                print(json.dumps({"error": msg}))
            else:
                print(f"error: {msg}", file=sys.stderr)
            return 2
        resolved.append(r)

    extra_axes = tuple(a.strip() for a in args.mesh_axes.split(",")
                       if a.strip())
    disabled = {r.strip() for r in args.disable.split(",") if r.strip()}
    min_rank = SEVERITY_RANK[args.severity]

    # expand the tree ONCE: lint_paths on plain file paths does no walk,
    # so the count and the linted set cannot disagree
    files = iter_python_files(resolved)
    findings = [
        f for f in lint_paths(files, extra_axes=extra_axes)
        if f.rule not in disabled and SEVERITY_RANK[f.severity] >= min_rank
    ]
    findings.sort(key=lambda f: (f.file or "", f.line or 0, f.rule))

    gate_hit = meets(findings, args.fail_on)
    counts = {"error": 0, "warning": 0, "note": 0}
    for f in findings:
        counts[f.severity] += 1
    n_files = len(files)
    if as_json:
        print(json.dumps({
            "ok": not gate_hit,
            "files": n_files,
            "fail_on": args.fail_on,
            "counts": counts,
            "findings": [f.to_dict() for f in findings],
        }))
    else:
        for f in findings:
            print(f.format())
        total = sum(counts.values())
        print(f"checked {n_files} file(s): {total} finding(s) "
              f"({counts['error']} error, {counts['warning']} warning, "
              f"{counts['note']} note)"
              + ("" if not gate_hit else
                 f" — failing (gate: {args.fail_on})"))
    return 1 if gate_hit else 0


def format_findings(findings: List[Finding]) -> str:
    """Convenience for embedding reports in exceptions/tests."""
    return "\n".join(f.format() for f in findings)
