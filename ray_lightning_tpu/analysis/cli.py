"""`python -m ray_lightning_tpu lint` / `... trace` — the shardcheck
and tracecheck CLIs.

Siblings of the doctor/plan subcommands (`__main__.py`): zero hardware,
run anywhere Python runs. `lint` targets are files, directories
(recursed), or importable dotted module names (resolved to their
source, never executed beyond the import machinery's parent-package
resolution). `trace` targets are bundled example names
(`llama_fsdp_example.py`), the `llama3-8b` preset, or a
`pkg.mod:factory` callable returning ``(module, strategy,
example_batch)`` — the factory IS imported and called.

Exit status (both): 0 clean (no finding at/above --fail-on), 1 findings
at or above the gate, 2 invalid invocation (missing path, unresolvable
module/target). With --json the report is ONE machine-readable JSON
object.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from ray_lightning_tpu.analysis.findings import (
    RULES, SEVERITY_RANK, Finding, meets,
)
from ray_lightning_tpu.analysis.linter import iter_python_files, lint_paths


def add_lint_parser(sub) -> None:
    """Attach the `lint` subparser (argparse) to `sub`."""
    p = sub.add_parser(
        "lint",
        help="static-analyze modules for sharding-plan and traced-code "
             "antipatterns (no TPU, no target imports)")
    p.add_argument(
        "targets", nargs="*", default=None,
        help="files, directories, or dotted module names (default: the "
             "installed ray_lightning_tpu package)")
    p.add_argument(
        "--severity", choices=("note", "warning", "error"), default="note",
        help="minimum severity to report (default: note — everything)")
    p.add_argument(
        "--fail-on", choices=("note", "warning", "error"), default="error",
        help="exit 1 when any finding is at/above this severity "
             "(default: error)")
    p.add_argument(
        "--disable", default="",
        help="comma-separated rule ids to drop entirely (e.g. RLT204)")
    p.add_argument(
        "--mesh-axes", default="",
        help="comma-separated EXTRA mesh-axis names to accept in "
             "PartitionSpec literals beyond the canonical six")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    p.add_argument(
        "--concurrency", action="store_true", dest="concurrency",
        default=None,
        help="also run threadcheck (RLT701-705: races, lock-order "
             "cycles, thread leaks, signal/lock hygiene). Default: on "
             "when linting the installed package (self-lint), off for "
             "explicit targets")
    p.add_argument(
        "--no-concurrency", action="store_false", dest="concurrency",
        help="skip threadcheck even on a package self-lint")
    p.add_argument(
        "--numerics", action="store_true", dest="numerics",
        default=None,
        help="also run numcheck's static pass (RLT801/805: inline "
             ".astype(bf16)/.astype(int8) operands pushed into dot/"
             "einsum calls). Default: on when linting the installed "
             "package (self-lint), off for explicit targets; the full "
             "dtype-provenance audit lives in `trace`")
    p.add_argument(
        "--no-numerics", action="store_false", dest="numerics",
        help="skip the static numerics pass even on a package "
             "self-lint")
    # same namespace-sharing contract as the plan subparser: a plain
    # default would clobber a `--json` given before the subcommand
    p.add_argument("--json", action="store_true", dest="as_json",
                   default=argparse.SUPPRESS)


def _resolve_target(target: str) -> Optional[str]:
    """A path stays a path; a dotted name resolves to its source file
    (or package directory)."""
    if os.path.exists(target):
        return target
    if os.sep in target or target.endswith(".py"):
        return None
    import importlib.util

    try:
        spec = importlib.util.find_spec(target)
    except (ImportError, ValueError, ModuleNotFoundError):
        return None
    if spec is None:
        return None
    if spec.submodule_search_locations:
        return list(spec.submodule_search_locations)[0]
    return spec.origin


def run_lint(args) -> int:
    as_json = getattr(args, "as_json", False)
    if args.list_rules:
        if as_json:
            print(json.dumps({rid: {
                "name": r.name, "severity": r.severity,
                "summary": r.summary} for rid, r in sorted(RULES.items())}))
        else:
            for rid, r in sorted(RULES.items()):
                print(f"{rid}  {r.severity:<8} {r.name}: {r.summary}")
        return 0

    targets = args.targets or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]
    resolved: List[str] = []
    for t in targets:
        r = _resolve_target(t)
        if r is None:
            msg = (f"no such file, directory, or importable module: "
                   f"{t!r}")
            if as_json:
                print(json.dumps({"error": msg}))
            else:
                print(f"error: {msg}", file=sys.stderr)
            return 2
        resolved.append(r)

    extra_axes = tuple(a.strip() for a in args.mesh_axes.split(",")
                       if a.strip())
    disabled = {r.strip() for r in args.disable.split(",") if r.strip()}
    min_rank = SEVERITY_RANK[args.severity]

    # expand the tree ONCE: lint_paths on plain file paths does no walk,
    # so the count and the linted set cannot disagree
    files = iter_python_files(resolved)
    all_findings = lint_paths(files, extra_axes=extra_axes)
    # threadcheck rides along: default-on for the package self-lint
    # (no explicit targets), opt-in/out via --concurrency/--no-concurrency
    concurrency = getattr(args, "concurrency", None)
    if concurrency is None:
        concurrency = not args.targets
    if concurrency:
        from ray_lightning_tpu.analysis.concurrency import (
            check_concurrency_paths,
        )

        all_findings = list(all_findings) + list(
            check_concurrency_paths(files))
    # numcheck's static mini-pass rides along under the same tri-state
    numerics = getattr(args, "numerics", None)
    if numerics is None:
        numerics = not args.targets
    if numerics:
        from ray_lightning_tpu.analysis.numcheck import (
            check_numerics_paths,
        )

        all_findings = list(all_findings) + list(
            check_numerics_paths(files))
    findings = [
        f for f in all_findings
        if f.rule not in disabled and SEVERITY_RANK[f.severity] >= min_rank
    ]
    findings.sort(key=lambda f: (f.file or "", f.line or 0, f.rule))

    gate_hit = meets(findings, args.fail_on)
    counts = {"error": 0, "warning": 0, "note": 0}
    for f in findings:
        counts[f.severity] += 1
    n_files = len(files)
    if as_json:
        print(json.dumps({
            "ok": not gate_hit,
            "files": n_files,
            "fail_on": args.fail_on,
            "counts": counts,
            "findings": [f.to_dict() for f in findings],
        }))
    else:
        for f in findings:
            print(f.format())
        total = sum(counts.values())
        print(f"checked {n_files} file(s): {total} finding(s) "
              f"({counts['error']} error, {counts['warning']} warning, "
              f"{counts['note']} note)"
              + ("" if not gate_hit else
                 f" — failing (gate: {args.fail_on})"))
    return 1 if gate_hit else 0


def format_findings(findings: List[Finding]) -> str:
    """Convenience for embedding reports in exceptions/tests."""
    return "\n".join(f.format() for f in findings)


# --------------------------------------------------------------------------
# trace — the tracecheck CLI
# --------------------------------------------------------------------------
#
# Every bundled example has a builder that reconstructs its (module,
# strategy, example batch) triple SIZED FOR THE TOPOLOGY, so
# `trace examples/llama_fsdp_example.py --topo v5p-64` audits the same
# step the example would compile on that slice — without running the
# example (examples parse argv, build trainers, and train).


def _build_llama_fsdp(topo, overlap: str = "off"):
    import numpy as np

    from ray_lightning_tpu.models.llama import LlamaConfig, LlamaModule
    from ray_lightning_tpu.parallel.strategy import ShardedMesh

    n = topo.n_devices
    # Multi-slice topologies (--topo 2xv5p-64): HSDP — the `data` axis
    # spans the slices (only gradient all-reduces cross DCN,
    # hierarchically reduced), fsdp stays inside each slice on ICI.
    # This is the placement the mesh layer enforces on real multi-slice
    # hardware (parallel/mesh.py order_devices_for_slices) and the one
    # tracecheck audits clean; an fsdp axis across slices flags RLT306.
    data = getattr(topo, "n_slices", 1)
    fsdp = n // data
    if n >= 16:
        # the BASELINE.json north-star config: 8B, remat+scan+fused CE,
        # flash attention (the program the TPU actually runs), one
        # 8192-token row per device
        cfg = LlamaConfig.llama3_8b(
            remat=True, scan_layers=True, fused_ce=True, use_flash=True,
            max_seq_len=8192)
        batch, seq = n, 8192
        label = (f"llama3-8b HSDP(data={data},fsdp={fsdp})" if data > 1
                 else f"llama3-8b FSDP({n})")
    else:
        cfg = LlamaConfig.tiny(use_flash=True)
        batch, seq = 2 * n, min(256, cfg.max_seq_len)
        label = (f"llama-tiny HSDP(data={data},fsdp={fsdp})" if data > 1
                 else f"llama-tiny FSDP({n})")
    if overlap != "off":
        label += f" overlap={overlap}"
    return (LlamaModule(cfg),
            ShardedMesh(data=data, fsdp=fsdp, overlap=overlap),
            {"tokens": np.zeros((batch, seq + 1), np.int32)}, label)


def _build_mlp(features, num_classes, in_dim, label):
    def build(topo):
        import numpy as np

        from ray_lightning_tpu.models.mlp import MLPClassifier
        from ray_lightning_tpu.parallel.strategy import DataParallel

        n = topo.n_devices
        B = 8 * n
        return (MLPClassifier(features=features, num_classes=num_classes),
                DataParallel(),
                {"x": np.zeros((B, in_dim), np.float32),
                 "y": np.zeros((B,), np.int32)},
                f"{label} DataParallel({n})")
    return build


def _build_cifar_resnet(topo):
    import numpy as np

    from ray_lightning_tpu.models.resnet import ResNetModule
    from ray_lightning_tpu.parallel.strategy import DataParallel

    n = topo.n_devices
    B = 8 * n
    return (ResNetModule(variant="resnet18", num_classes=10),
            DataParallel(),
            {"x": np.zeros((B, 32, 32, 3), np.float32),
             "y": np.zeros((B,), np.int32)},
            f"resnet18 DataParallel({n})")


def _build_bert_finetune(topo):
    import numpy as np

    from ray_lightning_tpu.models.bert import (
        BertClassifierModule, BertConfig,
    )
    from ray_lightning_tpu.parallel.strategy import DataParallel

    n = topo.n_devices
    B, S = 4 * n, 128
    cfg = BertConfig.tiny(dropout=0.0)
    return (BertClassifierModule(cfg, num_classes=2), DataParallel(),
            {"input_ids": np.zeros((B, S), np.int32),
             "labels": np.zeros((B,), np.int32)},
            f"bert-tiny DataParallel({n})")


_TRACE_BUILDERS = {
    "llama_fsdp_example.py": _build_llama_fsdp,
    "llama3-8b": _build_llama_fsdp,
    "mnist_dp_example.py": _build_mlp((128, 256), 10, 784, "mnist-mlp"),
    "mnist_sweep_example.py": _build_mlp((128, 256), 10, 784,
                                         "mnist-sweep-mlp"),
    "pod_launch_example.py": _build_mlp((64,), 4, 16, "pod-mlp"),
    "cifar_resnet_example.py": _build_cifar_resnet,
    "bert_finetune_example.py": _build_bert_finetune,
}


def add_trace_parser(sub) -> None:
    """Attach the `trace` subparser (argparse) to `sub`."""
    p = sub.add_parser(
        "trace",
        help="audit a strategy's REAL jitted train step at the jaxpr "
             "level: collective schedule + ICI cost, implicit "
             "resharding, ring checks, peak-HBM estimate (no TPU)")
    p.add_argument(
        "target",
        help="a bundled example (examples/llama_fsdp_example.py), the "
             "'llama3-8b' preset, or pkg.mod:factory returning "
             "(module, strategy, example_batch)")
    p.add_argument(
        "--topo", default="v5p-8",
        help="target topology <family>-<chips>, e.g. v5p-64, or a "
             "multi-slice deployment <slices>x<family>-<chips>, e.g. "
             "2xv5p-64 — two slices joined over DCN; the trace then "
             "itemizes ICI vs DCN bytes per step "
             "(families: v3 v4 v5e v5p v6e cpu)")
    p.add_argument(
        "--overlap", choices=("off", "on", "serial"), default="off",
        help="trace the llama targets with the collective-overlap "
             "schedule (strategy overlap= knob, docs/PERFORMANCE.md "
             "'collective overlap'); tracecheck then classifies each "
             "collective hidden-vs-exposed against the prefetch "
             "schedule it finds in the jaxpr")
    p.add_argument(
        "--hbm-bytes", type=int, default=None,
        help="per-device usable HBM override in bytes")
    p.add_argument(
        "--severity", choices=("note", "warning", "error"),
        default="note", help="minimum severity to report")
    p.add_argument(
        "--fail-on", choices=("note", "warning", "error"),
        default="error",
        help="exit 1 when any finding is at/above this severity")
    p.add_argument("--disable", default="",
                   help="comma-separated rule ids to drop (e.g. RLT302)")
    p.add_argument(
        "--numerics", action="store_true", dest="numerics", default=True,
        help="run numcheck's dtype-provenance pass over the traced "
             "jaxpr (RLT801-805) and report the precision ledger "
             "(default: on)")
    p.add_argument(
        "--no-numerics", action="store_false", dest="numerics",
        help="skip the numerics pass and the precision ledger")
    # same namespace-sharing contract as the plan/lint subparsers
    p.add_argument("--json", action="store_true", dest="as_json",
                   default=argparse.SUPPRESS)


def resolve_trace_target(target: str, topo, overlap: str = "off"):
    """Resolve a trace target to ``(module, strategy, batch, label)``.
    Returns None when the target is not recognizable (exit-2 path).
    ``overlap`` reaches builders that take the knob (the llama FSDP
    targets); others ignore it silently — the knob is advisory."""
    base = os.path.basename(target)
    builder = _TRACE_BUILDERS.get(base) or _TRACE_BUILDERS.get(target)
    if builder is not None:
        import inspect

        if "overlap" in inspect.signature(builder).parameters:
            return builder(topo, overlap=overlap)
        return builder(topo)
    if ":" in target and os.sep not in target:
        mod_name, _, fn_name = target.partition(":")
        import importlib

        try:
            factory = getattr(importlib.import_module(mod_name), fn_name)
        except (ImportError, AttributeError):
            return None
        built = factory()
        if isinstance(built, dict):
            return (built["module"], built["strategy"], built["batch"],
                    built.get("label", target))
        module, strategy, batch = built[:3]
        label = built[3] if len(built) > 3 else target
        return module, strategy, batch, label
    return None


def run_trace(args) -> int:
    as_json = getattr(args, "as_json", False)
    from ray_lightning_tpu.analysis.costmodel import parse_topology
    from ray_lightning_tpu.analysis.tracecheck import audit_step

    def invalid(msg: str) -> int:
        if as_json:
            print(json.dumps({"error": msg}))
        else:
            print(f"error: {msg}", file=sys.stderr)
        return 2

    try:
        topo = parse_topology(args.topo, hbm_bytes=args.hbm_bytes)
    except ValueError as exc:
        return invalid(str(exc))
    try:
        built = resolve_trace_target(args.target, topo,
                                     overlap=getattr(args, "overlap",
                                                     "off"))
    except Exception as exc:  # noqa: BLE001 — a factory that raises is
        # an invalid invocation, not a finding
        return invalid(f"building {args.target!r} failed: "
                       f"{type(exc).__name__}: {exc}")
    if built is None:
        return invalid(
            f"unknown trace target {args.target!r}; use a bundled "
            f"example ({sorted(set(_TRACE_BUILDERS) - {'llama3-8b'})}), "
            "the 'llama3-8b' preset, or pkg.mod:factory")
    module, strategy, batch, label = built

    report = audit_step(module, strategy, batch, topology=topo,
                        label=label,
                        numerics=getattr(args, "numerics", True))
    disabled = {r.strip() for r in args.disable.split(",") if r.strip()}
    min_rank = SEVERITY_RANK[args.severity]
    findings = [f for f in report.findings
                if f.rule not in disabled
                and SEVERITY_RANK[f.severity] >= min_rank]
    report.findings = findings
    gate_hit = meets(findings, args.fail_on)
    if as_json:
        print(json.dumps({"ok": not gate_hit, "fail_on": args.fail_on,
                          **report.to_dict()}))
    else:
        print(report.summary())
        if gate_hit:
            print(f"— failing (gate: {args.fail_on})")
    return 1 if gate_hit else 0
