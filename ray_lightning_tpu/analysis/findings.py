"""Finding/rule vocabulary shared by both shardcheck engines.

One `Finding` type and one rule registry serve the AST linter
(analysis/linter.py) and the abstract-interpretation plan checker
(analysis/plan_checker.py) so the CLI, the JSON artifact, and the
suppression syntax (`# rlt: disable=RULE`) are engine-agnostic: a rule id
means the same defect whether it was proven from source text or from an
eval_shape'd parameter pytree (RLT101/RLT103 are emitted by both).

Severity contract (docs/STATIC_ANALYSIS.md):
  error   — the training job will fail, silently mis-shard, or recompile
            per step at scale; the lint CLI's default fail gate
  warning — a footgun that costs memory/determinism but may be intended
  note    — informational
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

#: severity name -> rank, for threshold comparisons
SEVERITY_RANK: Dict[str, int] = {"note": 0, "warning": 1, "error": 2}

#: the TpuModule hooks the Trainer compiles under jax.jit — their bodies
#: run under a tracer. Defined HERE (the analysis package's only
#: dependency-free module) so the AST linter stays importable without
#: jax/optax; core/module.py re-exports it as the protocol constant.
TRACED_STEP_HOOKS: Tuple[str, ...] = (
    "training_step", "validation_step", "test_step", "predict_step",
)


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    severity: str  # default severity; findings may not override upward
    summary: str


#: every shardcheck rule, both engines (docs/STATIC_ANALYSIS.md is the
#: prose companion — keep the two in sync)
RULES: Dict[str, Rule] = {r.id: r for r in (
    Rule("RLT001", "parse-error", "error",
         "target file does not parse; nothing else can be checked"),
    Rule("RLT101", "unknown-mesh-axis", "error",
         "PartitionSpec names a mesh axis that does not exist (typo'd "
         "axes are silently dropped -> the leaf replicates -> OOM at "
         "scale)"),
    Rule("RLT102", "uneven-shard", "error",
         "a sharded dim is not divisible by its mesh axis product; the "
         "leaf cannot be partitioned evenly"),
    Rule("RLT103", "duplicate-mesh-axis", "error",
         "the same mesh axis appears twice in one PartitionSpec"),
    Rule("RLT104", "spec-rank-mismatch", "error",
         "PartitionSpec has more entries than the parameter has dims"),
    Rule("RLT105", "opt-dtype-widening", "warning",
         "optimizer-state leaf stored wider than its parameter "
         "(silent multi-x optimizer HBM)"),
    Rule("RLT106", "donation-mismatch", "error",
         "a donated input buffer has no output with matching "
         "shape/dtype/sharding to alias; the donation is wasted"),
    Rule("RLT107", "stale-spec-path", "warning",
         "param_specs path matches no parameter (renamed layer? the "
         "spec silently does nothing)"),
    Rule("RLT201", "host-transfer-in-step", "error",
         "host transfer (.item()/device_get/np.asarray/...) inside "
         "traced code forces a device sync per step"),
    Rule("RLT202", "python-rng-in-step", "error",
         "Python/numpy RNG inside traced code is baked in at trace "
         "time (same 'random' numbers every step); use jax.random"),
    Rule("RLT203", "wallclock-in-step", "warning",
         "time.time()/datetime.now() inside traced code runs at trace "
         "time only, not per step"),
    Rule("RLT204", "print-in-step", "warning",
         "print() inside traced code fires at trace time only; use "
         "jax.debug.print for runtime values"),
    Rule("RLT205", "unhashable-static-arg", "error",
         "static argument of a jitted function is unhashable (or names "
         "a parameter that does not exist) — TypeError or a recompile "
         "per call"),
    Rule("RLT206", "unordered-iteration", "warning",
         "iteration over an unordered collection (set/vars()) while "
         "building traced structure; pytree order can differ across "
         "processes"),
    # RLT3xx — the tracecheck engine (analysis/tracecheck.py): jaxpr-level
    # audit of the REAL jitted train step. The uppercase aliases below are
    # the vocabulary ISSUE/docs use in prose: RESHARD-IMPLICIT,
    # HBM-OVERCOMMIT, RING-DEADLOCK.
    Rule("RLT301", "reshard-implicit", "error",
         "in/out sharding mismatch makes XLA insert a collective the "
         "plan never asked for (an activation all-gather or a reshard "
         "between mesh axes) — silent ICI traffic every step"),
    Rule("RLT302", "hbm-overcommit", "error",
         "the traced step's estimated peak HBM (params + opt state + "
         "activation high-water mark) exceeds the target chip's budget; "
         "the job will OOM at compile or at runtime"),
    Rule("RLT304", "host-sync-in-hot-loop", "warning",
         "a per-batch training loop synchronizes with the device every "
         "step (float()/np.asarray()/.item()/block_until_ready on step "
         "outputs outside the log cadence) or places batches with an "
         "un-prefetched device_put on the critical path — each one "
         "drains the device dispatch queue; fetch on a cadence and use "
         "the device prefetch pipeline (docs/PERFORMANCE.md)"),
    Rule("RLT305", "exposed-collective-in-scan", "warning",
         "a blocking collective inside a scanned layer body whose "
         "operand is loop-invariant (a ZeRO/FSDP weight gather of a "
         "parameter slice — prefetchable one trip ahead) sits exposed "
         "on the critical path every trip; enable the sharding plan's "
         "overlap knob (FSDP/ShardedMesh(overlap='on')) to hide it "
         "behind the previous layer's compute "
         "(docs/PERFORMANCE.md 'collective overlap')"),
    Rule("RLT306", "dcn-crossing-shard-axis", "warning",
         "a tensor/fsdp/seq/expert/pipe mesh axis spans DCN slices on a "
         "multi-slice topology: its per-layer collectives (weight "
         "gathers, tensor psums, ring permutes) would ride the slow "
         "inter-slice network every step — an order-of-magnitude "
         "performance cliff. Only the `data` axis belongs across "
         "slices (hierarchical gradient reduction, docs/ELASTIC.md "
         "'DCN cost model'); re-shape the mesh so the crossing axis "
         "fits inside one slice"),
    Rule("RLT307", "dense-paged-gather", "warning",
         "a serving decode step materializes a dense slot-gathered KV "
         "view of the block-paged pool ([L, capacity, gathered_len, "
         "Hkv, hd] per tick — ~half the replica's serving HBM and a "
         "full pool copy of traffic) although the fused paged-attention "
         "kernel supports the shape: the kernel consumes the pool "
         "directly through the block tables and retires the copy "
         "(ops/pallas/paged_attention.py; selected automatically on "
         "TPU — docs/SERVING.md 'paged-attention kernel'). The "
         "cond-nested prefill gather is RLT308's domain"),
    Rule("RLT308", "dense-paged-prefill-gather", "warning",
         "a serving step's PREFILL lane materializes a dense "
         "group-sized KV view of the block-paged pool ([L, "
         "prefill_batch, gathered_len, Hkv, hd] per chunk — the last "
         "dense gather on the serving hot path, a per-chunk copy of "
         "HBM traffic) although the fused paged-prefill kernel "
         "supports the shape: the kernel attends causally through the "
         "block tables with the chunk's K/V scattered straight into "
         "owned pool blocks, and the gather never exists "
         "(ops/pallas/paged_prefill.py; selected automatically on TPU "
         "— docs/SERVING.md 'paged prefill kernel'). Shapes the "
         "kernel cannot tile keep the historical sanction"),
    Rule("RLT309", "redundant-prefix-prefill", "warning",
         "a serve-side loop submits one request per iteration whose "
         "prompt prepends a LOOP-INVARIANT prefix (a shared system "
         "prompt) without prefix_cache=True anywhere in the file: "
         "every request re-prefills the identical prefix tokens and "
         "pins its own pool copy of those blocks, so prefill compute "
         "and KV HBM both scale with the stream count instead of "
         "once. Arm the scheduler's prefix cache — the common prefix "
         "prefills ONCE and its full blocks map into every table by "
         "refcount, copy-on-write on divergence (serve/kv_cache.py "
         "PrefixCache, docs/SERVING.md 'prefix cache')"),
    Rule("RLT303", "ring-deadlock", "error",
         "a ppermute permutation is not a valid schedule (duplicate "
         "source/destination, out-of-range rank, a full permutation "
         "that is not a single cycle) or collective sequences diverge "
         "across cond branches — SPMD ranks deadlock or exchange "
         "garbage"),
    # RLT4xx — resilience anti-patterns (docs/RESILIENCE.md): code shapes
    # that defeat the supervision layer's failure classification.
    Rule("RLT402", "nan-through-where", "warning",
         "jnp.where(cond, f(x), safe) with f in log/sqrt/div/pow "
         "evaluates BOTH branches under jit: the untaken branch's NaN/"
         "inf flows back through its cotangent and poisons the whole "
         "gradient (the trap the trainguard then has to skip at "
         "runtime). Mask the INPUT (jnp.where(cond, x, 1.0) inside f), "
         "not the output. Also fires on unguarded jnp.log/jnp.sqrt of "
         "raw batch values in traced code"),
    Rule("RLT401", "unsupervised-worker-failure", "warning",
         "a bare/broad except silently swallows worker-group failures "
         "(WorkerError never reaches the supervisor, so a dead rank "
         "looks like success), or a started WorkerGroup has no "
         "shutdown() in a finally / context manager (a failure leaks "
         "worker processes and their hosts' chips)"),
    # RLT5xx — telemetry/observability misuse (docs/OBSERVABILITY.md):
    # instrumentation that itself becomes the overhead it measures.
    Rule("RLT501", "telemetry-misuse", "warning",
         "telemetry emission (TelemetryRecorder span/record/flush, "
         "profiler start/stop) inside a per-batch loop without a "
         "cadence guard — per-step file flushes/captures stall the hot "
         "loop the spans exist to measure (buffer in the bounded ring, "
         "flush on `if step %% N == 0`) — or an unbounded event-list "
         "append in a per-batch Callback hook with no ring/truncation/"
         "flush anywhere in the class (the list grows for the life of "
         "the run; use a deque(maxlen=...) or truncate)"),
    Rule("RLT502", "serve-loop-recompile", "warning",
         "a decode/serve loop calls a jitted function with a "
         "Python-varying shape (a sequence buffer grown by concatenate "
         "every iteration, or an argument sliced to an un-bucketed "
         "per-iteration length): every call silently retraces and "
         "recompiles, turning request churn into a compile storm. "
         "Keep device shapes fixed — decode into a position-indexed "
         "KV cache, pad prompts to buckets, or use the fixed-capacity "
         "slot engine (serve.DecodeEngine, docs/SERVING.md)"),
    Rule("RLT503", "unbounded-ledger-read", "warning",
         "a cadence-polled code path (a sleep-loop — monitor --follow, "
         "a controller poll, watch evaluation) parses an ENTIRE *.jsonl "
         "evidence ledger into memory every poll: the ledger grows for "
         "the life of the run, so the poll cost grows without bound "
         "and the live view eventually spends its whole interval "
         "re-parsing history it already saw. Thread a tail/window "
         "bound (read_spans/read_metrics tail_bytes=, load_signal "
         "window=) — the readers keep the clock-alignment header and "
         "the newest entries, which is all a live view needs"),
    Rule("RLT504", "per-token-channel-chatter", "warning",
         "a per-decode-tick loop does an unbatched channel send/recv "
         "PER TOKEN (a queue put / channel send / reader poll inside a "
         "for-loop over the tick's emissions): every emitted token "
         "pays a syscall + fsync + wakeup, so the wire chatter scales "
         "with tokens/tick instead of ticks, and the worker loop "
         "stalls on I/O the engine tick already amortized. Batch the "
         "tick's emissions into ONE side-channel item and ack ONE "
         "highest-seq per poll batch (serve/channel.py, "
         "docs/SERVING.md 'the request channel')"),
    Rule("RLT505", "silent-request-drop", "error",
         "serving code makes a request disappear without a typed "
         "record: a broad except whose body only passes wrapped "
         "around a submit()/enqueue() call, or take_sheds() drained "
         "as a bare statement (/ a last_sheds/last_preemptions "
         "buffer cleared unread) — the stream never gets a terminal "
         "status, the client retries blind, and the loss is "
         "invisible to watch/metrics. The graceful-overload contract "
         "is EXPLICIT degradation: every rejected rid ends with a "
         "reason + capped-exponential retry-after hint "
         "(docs/SERVING.md 'traffic & SLO classes')"),
    # RLT6xx — elasticity anti-patterns (docs/ELASTIC.md): code that
    # pins a job to one world size for life.
    Rule("RLT601", "pinned-world-size", "warning",
         "batch/rank math hardcodes a device count (a `batch // 8` / "
         "`world % 16` against an integer literal, or an ==/!= assert "
         "pinning jax.device_count()/len(jax.devices()) to a specific "
         "N): the code breaks the moment the elastic supervisor "
         "reshards the job onto a different world size. Derive the "
         "divisor from the mesh (parallel.mesh.batch_size_divisor, "
         "plan.dp_degree, MeshSpec.resolve) and gate on capability "
         "(> 1), not on a pinned count (docs/ELASTIC.md)"),
    # RLT7xx — threadcheck (analysis/concurrency.py): host-side
    # concurrency. The host orchestration around jit is a real threaded
    # system (prefetch producer, checkpoint finalizer, heartbeats,
    # report servers); these rules audit it the way RLT1xx audits the
    # sharding plan. RLT702/RLT705 are also emitted at RUNTIME by the
    # lock-order sanitizer (analysis/lockwatch.py) — same id, proven by
    # observation instead of from source text.
    Rule("RLT701", "unguarded-shared-mutation", "error",
         "an instance attribute is WRITTEN in thread-reachable code "
         "(the body of a threading.Thread target, or anything it calls "
         "in-file) and read or written outside it with no common lock "
         "held at both sites — a data race on host state. Guard both "
         "sides with one lock, or hand the value over through a "
         "synchronized carrier (queue.Queue, threading.Event, "
         "deque(maxlen=...) — their receivers are sanctioned as their "
         "own synchronization)"),
    Rule("RLT702", "lock-order-inversion", "error",
         "the package-wide lock-acquisition graph (lock B acquired "
         "while lock A is held, from nested `with` chains and "
         "cross-function calls) contains a cycle: two threads taking "
         "the locks in opposite orders can deadlock. Impose one global "
         "order, or narrow one critical section so the locks are never "
         "held together"),
    Rule("RLT703", "thread-leak", "warning",
         "a started non-daemon thread has no join() on any path (not "
         "joined in the spawning scope, a finally, or a close/shutdown "
         "method of the owning class): process exit blocks on it "
         "forever. Join it on the exit path, or mark it daemon=True if "
         "abandoning mid-work is genuinely safe"),
    Rule("RLT704", "signal-unsafe-handler", "warning",
         "a signal.signal handler does more than flag-and-return "
         "(set a flag/Event, os.write to a raw fd, os._exit) — locks, "
         "print/logging, file I/O, or queue ops inside a handler can "
         "deadlock on the interrupted thread's own held resources. "
         "The bench.py/preempt.py discipline: the handler records, the "
         "loop reacts at the next batch boundary"),
    Rule("RLT705", "blocking-call-under-lock", "warning",
         "a blocking call (sleep, thread join, subprocess, untimed "
         "queue.get/put, file/socket I/O) runs while a lock is held, "
         "stalling every thread contending for it. Copy state out "
         "under the lock and do the slow work outside. A lock whose "
         "EVERY critical section is the same I/O (a dedicated "
         "append-serialization lock) is sanctioned — the hazard is a "
         "lock that also guards in-memory state"),
    # RLT8xx — numcheck (analysis/numcheck.py): jaxpr-level mixed-
    # precision flow audit. The dtype model and every sanction are
    # documented in docs/STATIC_ANALYSIS.md "numcheck — the precision
    # layer"; RLT805 is the contract the int8-KV campaign (ROADMAP
    # item 2c) compiles against.
    Rule("RLT801", "low-precision-accumulation", "error",
         "a dot_general or reduce-sum accumulates in bf16/f16 over a "
         "large contraction extent (missing "
         "preferred_element_type=f32): each bf16 add keeps 8 mantissa "
         "bits, so a K-term sum loses ~log2(K) of them — at K=4096 "
         "half the mantissa is noise. Small extents are sanctioned "
         "(the error is bounded by the extent)"),
    Rule("RLT802", "unstable-primitive-in-low-precision", "warning",
         "exp/log/rsqrt (the softmax/logsumexp/variance building "
         "blocks) computed on a bf16/f16 value with no f32 upcast: "
         "exp overflows bf16 at x>88 unless the operand is max-"
         "subtracted (sub-max inputs are sanctioned), log/rsqrt lose "
         "their low-order bits exactly where the result is largest. "
         "The pallas kernels' f32 scratch is sanctioned by "
         "construction (their operands are already f32)"),
    Rule("RLT803", "cast-churn", "warning",
         "an f32 value is rounded to bf16/f16 and converted straight "
         "back to f32 with no compute in between (only layout ops or "
         "a scan carry boundary): the round trip buys nothing, costs "
         "a rounding, and writes both copies through HBM"),
    Rule("RLT804", "low-precision-gradient-collective", "error",
         "a gradient psum/reduce_scatter runs on a bf16/f16 payload "
         "whose optimizer state is stored wider (f32): the ring "
         "reduction accumulates in the wire dtype, so the N-shard sum "
         "loses precision BEFORE the optimizer ever sees it — widen "
         "the gradient (preferred_element_type=f32 on the backward "
         "matmuls) so the reduction rides f32"),
    Rule("RLT805", "quant-contract", "error",
         "an int8/int4-origin value is consumed by float arithmetic "
         "with no dequantization scale applied (no multiply by an "
         "f32 scale between the int load and the math), or its scale "
         "is itself narrower than f32: the quantized payload is "
         "garbage without its scale, and a bf16 scale re-quantizes "
         "the error the int8 encoding already paid for"),
)}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One defect, pointing either at source (file/line/col) or at a
    pytree location (symbol, e.g. a param path)."""

    rule: str
    message: str
    severity: Optional[str] = None  # default: the rule's severity
    file: Optional[str] = None
    line: Optional[int] = None
    col: Optional[int] = None
    symbol: Optional[str] = None

    def __post_init__(self):
        if self.rule not in RULES:
            raise ValueError(f"unknown rule id {self.rule!r}")
        if self.severity is None:
            object.__setattr__(self, "severity", RULES[self.rule].severity)
        elif self.severity not in SEVERITY_RANK:
            raise ValueError(f"unknown severity {self.severity!r}")

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "name": RULES[self.rule].name,
             "severity": self.severity, "message": self.message}
        for k in ("file", "line", "col", "symbol"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        return d

    def format(self) -> str:
        loc = ""
        if self.file is not None:
            loc = self.file
            if self.line is not None:
                loc += f":{self.line}"
                if self.col is not None:
                    loc += f":{self.col}"
            loc += ": "
        elif self.symbol is not None:
            loc = f"{self.symbol}: "
        tail = f" [{self.symbol}]" if self.file and self.symbol else ""
        return (f"{loc}{self.severity} {self.rule} "
                f"({RULES[self.rule].name}): {self.message}{tail}")


def max_severity(findings) -> int:
    """Highest severity rank present (-1 when clean)."""
    return max((SEVERITY_RANK[f.severity] for f in findings), default=-1)


def meets(findings, threshold: str) -> bool:
    """True when any finding is at or above `threshold`."""
    return max_severity(findings) >= SEVERITY_RANK[threshold]
