"""shardcheck code linter: AST pass over user modules for TPU/JAX
antipatterns that surface as per-step host syncs, recompile storms, or
cross-process nondeterminism only AFTER minutes of pod queueing.

Zero hardware, zero target-module imports: files are parsed, never
executed, so a module with a top-level `jax.distributed.initialize()`
lints as safely as a pure one.

What counts as *traced code* (the scope where the RLT2xx rules fire):

  * the TpuModule step hooks (training_step/validation_step/test_step/
    predict_step — core/module.py TRACED_STEP_HOOKS): the Trainer jits
    them, so their bodies run under a tracer;
  * functions decorated with jit-family transforms (`@jax.jit`,
    `@partial(jax.jit, ...)`, `@nn.compact`, `@nn.remat`,
    `@jax.checkpoint`, `@jax.custom_vjp`, grad/vmap/scan wrappers);
  * local functions passed to a jit-family call (`step = jax.jit(step)`);
  * anything those functions call, resolved within the same file
    (`self.helper(...)` -> the method; `helper(...)` -> the module-level
    def) to a fixpoint — a host transfer hidden two helpers deep under
    `training_step` is still a host transfer per step.

Mesh-axis literal rules (RLT101/RLT103) fire anywhere in the file: a
`PartitionSpec("fdsp")` typo is wrong wherever it appears, and today's
composition logic would silently DROP the unknown axis (the leaf
replicates — the exact OOM-at-scale the motivation names).

Suppression: `# rlt: disable=RLT201` (comma-separate for several, bare
`# rlt: disable` for all) on the offending line;
`# rlt: disable-file=RLT204` anywhere disables a rule for the file.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ray_lightning_tpu.analysis.findings import (  # noqa: F401
    Finding, TRACED_STEP_HOOKS,
)

#: canonical mesh-axis vocabulary (parallel/mesh.py AXIS_ORDER, inlined
#: so the linter parses files without importing jax)
KNOWN_MESH_AXES: Tuple[str, ...] = (
    "data", "pipe", "fsdp", "expert", "seq", "tensor",
)

#: dotted names that make the decorated/wrapped function traced
_TRACE_TRANSFORMS: Set[str] = {
    "jax.jit", "jit", "pjit", "jax.pmap", "pmap",
    "nn.compact", "nn.remat", "nn.jit", "flax.linen.compact",
    "jax.checkpoint", "checkpoint", "jax.remat", "remat",
    "jax.custom_vjp", "custom_vjp", "jax.custom_jvp", "custom_jvp",
    "jax.vmap", "vmap", "jax.grad", "grad",
    "jax.value_and_grad", "value_and_grad",
    "jax.eval_shape", "jax.lax.scan", "lax.scan",
}

_HOST_TRANSFER_CALLS: Set[str] = {
    "jax.device_get", "jax.block_until_ready",
    "np.asarray", "np.array", "np.ascontiguousarray",
    "numpy.asarray", "numpy.array", "numpy.ascontiguousarray",
}
_HOST_TRANSFER_METHODS: Set[str] = {
    "item", "tolist", "block_until_ready", "numpy",
}

_WALLCLOCK_CALLS: Set[str] = {
    "time.time", "time.time_ns", "time.perf_counter", "time.monotonic",
    "time.process_time", "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
}

_RNG_ROOTS: Tuple[str, ...] = ("random.", "np.random.", "numpy.random.")

_SUPPRESS_RE = re.compile(
    r"#\s*rlt:\s*disable(?P<scope>-file)?(?:\s*=\s*(?P<rules>[A-Z0-9,\s]+))?"
)


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_trace_transform(expr: ast.AST) -> bool:
    """True when `expr` (a decorator or a call's func) is a jit-family
    transform — directly, or through `partial(jax.jit, ...)`."""
    name = _dotted(expr)
    if name in _TRACE_TRANSFORMS:
        return True
    if isinstance(expr, ast.Call):
        fname = _dotted(expr.func)
        if fname in _TRACE_TRANSFORMS:
            return True  # e.g. @jax.checkpoint(policy=...)
        if fname in ("partial", "functools.partial") and expr.args:
            return _is_trace_transform(expr.args[0])
    return False


class _Func:
    """One function/method with enough context for traced-set fixpoint."""

    __slots__ = ("node", "qualname", "cls", "parent", "calls", "traced")

    def __init__(self, node, qualname: str, cls: Optional[str],
                 parent: Optional["_Func"]):
        self.node = node
        self.qualname = qualname
        self.cls = cls
        self.parent = parent
        self.calls: Set[Tuple[str, str]] = set()  # ("self"|"name", name)
        self.traced = False


class _Collector(ast.NodeVisitor):
    """First pass: function table, call edges, traced seeds, and the
    spec-literal checks (which are scope-independent)."""

    def __init__(self, linter: "_FileLint"):
        self.lint = linter
        self._cls: List[str] = []
        self._fn: List[_Func] = []
        self.funcs: List[_Func] = []
        #: simple name -> funcs (cheap resolution for bare calls)
        self.by_name: Dict[str, List[_Func]] = {}
        #: (cls, name) -> func, for self.x(...) resolution
        self.by_method: Dict[Tuple[str, str], _Func] = {}
        self.spec_ctors: Set[str] = {"PartitionSpec"}

    # ---- imports: which local names mean PartitionSpec -------------------

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module and node.module.startswith("jax"):
            for alias in node.names:
                if alias.name == "PartitionSpec":
                    self.spec_ctors.add(alias.asname or alias.name)
        self.generic_visit(node)

    # ---- function table --------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef):
        self._cls.append(node.name)
        self.generic_visit(node)
        self._cls.pop()

    def _handle_func(self, node):
        cls = self._cls[-1] if self._cls else None
        parent = self._fn[-1] if self._fn else None
        prefix = (parent.qualname + ".") if parent else (
            (cls + ".") if cls else "")
        fn = _Func(node, prefix + node.name, cls, parent)
        self.funcs.append(fn)
        self.by_name.setdefault(node.name, []).append(fn)
        if cls is not None and parent is None:
            self.by_method[(cls, node.name)] = fn

        if any(_is_trace_transform(d) for d in node.decorator_list):
            fn.traced = True
        if cls is not None and node.name in TRACED_STEP_HOOKS:
            fn.traced = True

        self._check_static_args(fn)

        self._fn.append(fn)
        self.generic_visit(node)
        self._fn.pop()

    visit_FunctionDef = _handle_func
    visit_AsyncFunctionDef = _handle_func

    # ---- calls: edges, call-form jit, spec literals ----------------------

    def visit_Call(self, node: ast.Call):
        cur = self._fn[-1] if self._fn else None
        if cur is not None:
            if isinstance(node.func, ast.Name):
                cur.calls.add(("name", node.func.id))
            elif (isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"):
                cur.calls.add(("self", node.func.attr))

        # call-form wrapping: jax.jit(step, ...) makes local `step` traced
        if _is_trace_transform(node.func) and node.args:
            target = node.args[0]
            if isinstance(target, ast.Name):
                for fn in self.by_name.get(target.id, ()):
                    fn.traced = True
            self._check_static_args_call(node)

        fname = _dotted(node.func)
        if fname is not None and (
                fname in self.spec_ctors
                or fname.split(".")[-1] == "PartitionSpec"):
            self._check_spec_literal(node)
        self.generic_visit(node)

    # ---- rule bodies -----------------------------------------------------

    def _check_spec_literal(self, node: ast.Call):
        axes: List[Tuple[str, ast.AST]] = []
        for arg in node.args:
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                axes.append((arg.value, arg))
            elif isinstance(arg, ast.Tuple):
                for el in arg.elts:
                    if (isinstance(el, ast.Constant)
                            and isinstance(el.value, str)):
                        axes.append((el.value, el))
        seen: Set[str] = set()
        for name, anode in axes:
            if name not in self.lint.known_axes:
                self.lint.add(
                    "RLT101",
                    f"PartitionSpec axis {name!r} is not a mesh axis "
                    f"(known: {', '.join(self.lint.known_axes)}); the "
                    "composition logic would silently drop it and "
                    "replicate the leaf",
                    anode)
            if name in seen:
                self.lint.add(
                    "RLT103",
                    f"mesh axis {name!r} used twice in one PartitionSpec",
                    anode)
            seen.add(name)

    def _static_names(self, call: ast.Call) -> Tuple[List[int], List[str]]:
        nums: List[int] = []
        names: List[str] = []
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                nums.extend(v for v in _const_seq(kw.value)
                            if isinstance(v, int))
            elif kw.arg == "static_argnames":
                names.extend(v for v in _const_seq(kw.value)
                             if isinstance(v, str))
        return nums, names

    def _check_static_args(self, fn: _Func):
        """Decorator form: @partial(jax.jit, static_argnums=...) over a
        def whose static params must exist and be hashable."""
        for deco in fn.node.decorator_list:
            call = deco
            if (isinstance(deco, ast.Call)
                    and _dotted(deco.func) in ("partial", "functools.partial")
                    and deco.args and _is_trace_transform(deco.args[0])):
                call = deco
            elif not (isinstance(deco, ast.Call)
                      and _is_trace_transform(deco.func)):
                continue
            self._check_static_against(call, fn.node)

    def _check_static_args_call(self, node: ast.Call):
        """Call form: jax.jit(f, static_argnames=...) with local f."""
        target = node.args[0]
        if not isinstance(target, ast.Name):
            return
        defs = self.by_name.get(target.id, ())
        for fn in defs:
            self._check_static_against(node, fn.node)

    def _check_static_against(self, call: ast.Call, fndef):
        nums, names = self._static_names(call)
        if not nums and not names:
            return
        args = fndef.args
        params = ([a.arg for a in args.posonlyargs]
                  + [a.arg for a in args.args])
        kwonly = [a.arg for a in args.kwonlyargs]
        defaults: Dict[str, ast.AST] = {}
        pos_defaults = args.defaults
        for p, d in zip(params[len(params) - len(pos_defaults):],
                        pos_defaults):
            defaults[p] = d
        for p, d in zip(kwonly, args.kw_defaults):
            if d is not None:
                defaults[p] = d
        for i in nums:
            if i >= len(params):
                self.lint.add(
                    "RLT205",
                    f"static_argnums={i} is out of range for "
                    f"{fndef.name}() ({len(params)} positional params)",
                    call)
            elif _unhashable_default(defaults.get(params[i])):
                self.lint.add(
                    "RLT205",
                    f"static arg {params[i]!r} of {fndef.name}() has an "
                    "unhashable default (list/dict/set) — jit will "
                    "TypeError or retrace per call",
                    call)
        for n in names:
            if n not in params and n not in kwonly:
                self.lint.add(
                    "RLT205",
                    f"static_argnames names {n!r} which is not a "
                    f"parameter of {fndef.name}() — the intended arg "
                    "stays traced and every new value recompiles",
                    call)
            elif _unhashable_default(defaults.get(n)):
                self.lint.add(
                    "RLT205",
                    f"static arg {n!r} of {fndef.name}() has an "
                    "unhashable default (list/dict/set) — jit will "
                    "TypeError or retrace per call",
                    call)


def _const_seq(node: ast.AST) -> List:
    if isinstance(node, ast.Constant):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [el.value for el in node.elts
                if isinstance(el, ast.Constant)]
    return []


def _unhashable_default(node: Optional[ast.AST]) -> bool:
    if node is None:
        return False
    if isinstance(node, (ast.List, ast.Dict, ast.Set,
                         ast.ListComp, ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return _dotted(node.func) in ("list", "dict", "set")
    return False


def _is_unordered_iterable(node: ast.AST) -> Optional[str]:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "a set literal"
    if isinstance(node, ast.Call):
        name = _dotted(node.func)
        if name in ("set", "frozenset"):
            return f"{name}()"
        if name == "vars":
            return "vars()"
    if isinstance(node, ast.Attribute) and node.attr == "__dict__":
        return "__dict__"
    return None


class _FileLint:
    """Per-file state: source, suppressions, findings."""

    def __init__(self, source: str, filename: str,
                 extra_axes: Sequence[str] = ()):
        self.filename = filename
        self.known_axes = tuple(KNOWN_MESH_AXES) + tuple(extra_axes)
        self.findings: List[Finding] = []
        self._line_off: Dict[int, Set[str]] = {}
        self._file_off: Set[str] = set()
        for i, text in enumerate(source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in (m.group("rules") or "").split(",")
                     if r.strip()} or {"*"}
            if m.group("scope"):
                self._file_off |= rules
            else:
                self._line_off.setdefault(i, set()).update(rules)

    def add(self, rule: str, message: str, node: Optional[ast.AST] = None,
            symbol: Optional[str] = None):
        line = getattr(node, "lineno", None)
        off = self._line_off.get(line, set()) | self._file_off
        if rule in off or "*" in off:
            return
        self.findings.append(Finding(
            rule=rule, message=message, file=self.filename, line=line,
            col=getattr(node, "col_offset", None), symbol=symbol,
        ))


def _own_nodes(fn_node) -> Iterable[ast.AST]:
    """All nodes of a function body EXCLUDING nested function defs (each
    nested def is linted as its own traced function); lambdas belong to
    the enclosing function."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# ---- RLT402: NaN through the untaken where branch --------------------------

#: math whose derivative (or value) is non-finite outside its domain —
#: the functions the classic jnp.where gradient trap involves
_RLT402_RISKY: Set[str] = {
    "log", "log1p", "log2", "log10", "sqrt", "rsqrt", "reciprocal",
    "divide", "true_divide", "power", "float_power",
    "arcsin", "arccos", "arctanh",
}

#: wrappers that mask/clamp the INPUT — their subtree is considered
#: guarded and never flagged
_RLT402_GUARDS: Set[str] = {
    "clip", "maximum", "minimum", "abs", "where", "nan_to_num",
    "relu", "softplus", "exp", "clamp", "logaddexp", "logsumexp",
}

_RLT402_ROOTS = ("jnp", "jax")


def _rlt402_is_jnp(name: Optional[str]) -> bool:
    return bool(name) and (name.startswith("jnp.")
                           or name.startswith("jax.numpy."))


def _rlt402_risky_in(expr: ast.AST) -> Optional[str]:
    """A risky op inside ``expr`` (skipping guarded subtrees), described
    for the message, else None."""
    stack: List[ast.AST] = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            name = _dotted(node.func) or ""
            last = name.split(".")[-1]
            if last in _RLT402_GUARDS:
                continue  # the input is masked — do not descend
            if _rlt402_is_jnp(name) and last in _RLT402_RISKY:
                if node.args and _rlt402_guarded(node.args[0]):
                    continue  # f(clamped_input): the sanctioned fix
                return f"{name}()"
        elif isinstance(node, ast.BinOp):
            if isinstance(node.op, ast.Div) and not _rlt402_guarded(
                    node.right):
                # x / jnp.maximum(d, eps) is the sanctioned fix and
                # must not be flagged — only an unguarded denominator
                return "a division"
            if isinstance(node.op, ast.Pow) and _rlt402_pow_risky(node):
                return "a power"
        stack.extend(ast.iter_child_nodes(node))
    return None


def _rlt402_pow_risky(node: ast.BinOp) -> bool:
    """x ** k is finite-gradient for positive-integer constant k; only
    fractional/negative/variable exponents (x**0.5 == sqrt, x**-1 ==
    reciprocal) hit the invalid-domain trap — and a clamped base is the
    sanctioned fix."""
    exp = node.right
    if (isinstance(exp, ast.Constant) and isinstance(exp.value, int)
            and exp.value >= 1):
        return False
    return not _rlt402_guarded(node.left)


def _rlt402_guarded(expr: ast.AST) -> bool:
    """True when the expression already masks its input: a guard call
    anywhere inside, or an additive epsilon (x + 1e-6)."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            last = (_dotted(node.func) or "").split(".")[-1]
            if last in _RLT402_GUARDS:
                return True
        elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            return True
    return False


def _lint_rlt402_call(lint: _FileLint, node: ast.Call,
                      fname: Optional[str], sym: str) -> None:
    if _rlt402_is_jnp(fname) and fname.split(".")[-1] == "where" \
            and len(node.args) == 3:
        for branch, which in ((node.args[1], "taken"),
                              (node.args[2], "untaken")):
            risky = _rlt402_risky_in(branch)
            if risky:
                lint.add(
                    "RLT402",
                    f"{risky} inside a jnp.where branch: under jit "
                    "BOTH branches evaluate, and the "
                    f"{which}-branch NaN/inf flows back through its "
                    "cotangent into the whole gradient — mask the "
                    "INPUT (jnp.where(cond, x, 1.0) inside the op), "
                    "not the output", node, sym)
                break  # one finding per where-call is enough
        return
    if (_rlt402_is_jnp(fname)
            and fname.split(".")[-1] in ("log", "log1p", "log2",
                                         "log10", "sqrt", "rsqrt")
            and node.args):
        arg = node.args[0]
        if _root_name(arg) == "batch" and not _rlt402_guarded(arg):
            lint.add(
                "RLT402",
                f"{fname}() on a raw batch value: one out-of-domain "
                "row (a zero, a negative) makes the loss NaN for the "
                "whole step — clamp or shift the input "
                "(jnp.maximum(x, eps)) before the transform", node, sym)


def _lint_traced_body(lint: _FileLint, fn: _Func) -> None:
    sym = fn.qualname
    for node in _own_nodes(fn.node):
        if isinstance(node, ast.Call):
            fname = _dotted(node.func)
            if fname in _HOST_TRANSFER_CALLS:
                lint.add("RLT201",
                         f"{fname}() inside traced code is a host "
                         "transfer — a device sync every step; keep "
                         "values on device (or move this out of the "
                         "step)", node, sym)
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _HOST_TRANSFER_METHODS
                    and not node.args and not node.keywords):
                lint.add("RLT201",
                         f".{node.func.attr}() inside traced code is a "
                         "host transfer — a device sync every step",
                         node, sym)
            elif fname is not None and fname.startswith(_RNG_ROOTS):
                lint.add("RLT202",
                         f"{fname}() is Python/numpy RNG: its value is "
                         "baked in at trace time, so every step reuses "
                         "the same 'random' numbers — thread a jax "
                         "PRNG key instead", node, sym)
            elif fname in _WALLCLOCK_CALLS:
                lint.add("RLT203",
                         f"{fname}() runs at trace time only — the "
                         "compiled step will reuse one stale timestamp "
                         "forever", node, sym)
            elif fname == "print":
                lint.add("RLT204",
                         "print() in traced code fires once, at trace "
                         "time, showing tracers not values — use "
                         "jax.debug.print for runtime values", node, sym)
            else:
                _lint_rlt402_call(lint, node, fname, sym)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            what = _is_unordered_iterable(node.iter)
            if what:
                lint.add("RLT206",
                         f"iterating {what} in traced code: unordered "
                         "iteration makes pytree/program order "
                         "nondeterministic across processes — sort it",
                         node, sym)
        elif isinstance(node, ast.comprehension):
            what = _is_unordered_iterable(node.iter)
            if what:
                lint.add("RLT206",
                         f"comprehension over {what} in traced code: "
                         "unordered iteration makes pytree/program "
                         "order nondeterministic across processes — "
                         "sort it", node.iter, sym)


# ---- RLT304: host sync in the per-batch hot loop --------------------------

#: iterator names that mark a `for` loop as a per-batch training/eval
#: loop (the RLT304 scope). Deliberately specific — `data` alone would
#: flag every list walk in sight.
_LOADER_NAME_TOKENS: Tuple[str, ...] = (
    "loader", "dataloader", "batches", "dataiter",
)

#: calls flagged on step outputs inside the hot loop (outside cadence)
_HOT_SYNC_CALLS: Set[str] = {
    "float", "int",
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "jax.device_get", "device_get",
    "jax.block_until_ready",
}

_HOT_SYNC_METHODS: Set[str] = {"item", "tolist", "block_until_ready"}


def _loader_like(expr: ast.AST) -> bool:
    """Does this `for` iterator look like a per-batch data source?"""
    if isinstance(expr, ast.Call):
        fname = _dotted(expr.func) or ""
        last = fname.split(".")[-1].lower()
        if last in ("enumerate", "iter", "zip", "islice"):
            return any(_loader_like(a) for a in expr.args)
        # loader factories: train_dataloader(), DataLoader(...)
        return "dataloader" in last
    name = _dotted(expr)
    if name is None:
        return False
    last = name.split(".")[-1].lower()
    return last == "dl" or any(t in last for t in _LOADER_NAME_TOKENS)


def _under_cadence_guard(node: ast.AST, parents: Dict[int, ast.AST]) -> bool:
    """True when `node` sits under an `if` whose test contains a `%`
    (the `if step % N == 0:` log-cadence idiom) — a sync every N steps
    is the sanctioned pattern, not the per-step bug."""
    cur = parents.get(id(node))
    while cur is not None:
        if isinstance(cur, ast.If) and any(
                isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mod)
                for n in ast.walk(cur.test)):
            return True
        cur = parents.get(id(cur))
    return False


def _root_name(expr: ast.AST) -> Optional[str]:
    """The base Name of a value expression: metrics / metrics["loss"] /
    out.loss → "metrics"/"out"."""
    while isinstance(expr, (ast.Subscript, ast.Attribute, ast.Starred)):
        expr = expr.value
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _walk_own_loop(stmts: List[ast.stmt]) -> Iterable[ast.AST]:
    """Nodes of one hot loop's body, EXCLUDING nested function defs and
    nested loader-like `for` loops — each nested hot loop is linted as
    its own loop (walking into it here would report its findings twice:
    once for the outer loop, once for its own pass)."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.For) and _loader_like(node.iter):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _loop_step_outputs(loop: ast.For) -> Set[str]:
    """Names assigned inside the loop body from a call whose callee name
    contains 'step' — the step outputs whose per-batch host fetch RLT304
    flags. Tuple unpacking (`state, metrics = step(...)`) counts."""
    outs: Set[str] = set()
    for node in _walk_own_loop(loop.body):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        callee = _dotted(node.value.func) or ""
        if "step" not in callee.split(".")[-1].lower():
            continue
        for tgt in node.targets:
            elts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
            for el in elts:
                if isinstance(el, ast.Name):
                    outs.add(el.id)
    return outs


#: method names that emit/persist telemetry when called on a
#: telemetry-shaped receiver (RLT501 arm A)
_TELEMETRY_METHODS: Set[str] = {
    "span", "record", "emit", "flush", "start_trace", "stop_trace",
}

#: receiver-name tokens that mark an object as telemetry machinery
_TELEMETRY_TOKENS: Tuple[str, ...] = (
    "telemetry", "recorder", "tracer", "profiler", "span",
)


def _telemetry_call(node: ast.Call) -> Optional[str]:
    """A human-readable description when this call is telemetry emission
    (RLT501 arm A), else None."""
    if isinstance(node.func, ast.Attribute):
        meth = node.func.attr
        if meth not in _TELEMETRY_METHODS:
            return None
        recv = _dotted(node.func.value) or ""
        low = recv.lower()
        if any(tok in low for tok in _TELEMETRY_TOKENS):
            return f"{recv}.{meth}"
        return None
    fname = _dotted(node.func) or ""
    if fname.split(".")[-1] in ("record_span", "emit_span"):
        return fname
    return None


def _lint_hot_loop(lint: _FileLint, loop: ast.For,
                   symbol: Optional[str]) -> None:
    step_outputs = _loop_step_outputs(loop)
    # parent links within the loop body, for the cadence-guard walkup
    parents: Dict[int, ast.AST] = {}
    for node in _walk_own_loop(loop.body):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    for node in _walk_own_loop(loop.body):
        if not isinstance(node, ast.Call):
            continue
        if _under_cadence_guard(node, parents):
            continue
        tele = _telemetry_call(node)
        if tele is not None:
            lint.add(
                "RLT501",
                f"{tele}() inside the per-batch loop outside a cadence "
                "guard — hand-rolled per-step telemetry puts flushes/"
                "captures (and whatever backs this recorder) on the hot "
                "path it exists to measure. Use the trainer's built-in "
                "instrumentation (Trainer(telemetry=...) already spans "
                "these seams from a bounded ring), or guard the call "
                "with the log cadence (if step % N == 0) "
                "(docs/OBSERVABILITY.md)", node, symbol)
            continue
        fname = _dotted(node.func)
        if fname is not None and fname.split(".")[-1] == "device_put":
            lint.add(
                "RLT304",
                "un-prefetched device_put in the per-batch loop: the "
                "host->device placement sits on the critical path "
                "every step — overlap it with compute "
                "(pipeline.DevicePrefetcher / "
                "Trainer(prefetch_to_device=N))", node, symbol)
            continue
        target: Optional[ast.AST] = None
        what = None
        if fname in _HOT_SYNC_CALLS and node.args:
            target = node.args[0]
            what = f"{fname}()"
        elif (isinstance(node.func, ast.Attribute)
                and node.func.attr in _HOT_SYNC_METHODS
                and not node.args and not node.keywords):
            target = node.func.value
            what = f".{node.func.attr}()"
        if target is None:
            continue
        root = _root_name(target)
        if root is not None and root in step_outputs:
            lint.add(
                "RLT304",
                f"{what} on step output {root!r} inside the "
                "per-batch loop forces a device sync every step — "
                "the dispatch queue drains and the accelerator "
                "idles; fetch on the log cadence "
                "(if step % N == 0) or keep it on device", node,
                symbol)


class _HotLoopLint:
    """RLT304 driver: finds per-batch loops in NON-traced code (traced
    bodies are RLT201 territory) — both inside functions and at module
    level — and lints each."""

    def __init__(self, lint: _FileLint):
        self.lint = lint

    def run(self, tree: ast.Module, funcs: List["_Func"]) -> None:
        for fn in funcs:
            if fn.traced:
                continue
            for node in _own_nodes(fn.node):
                if isinstance(node, ast.For) and _loader_like(node.iter):
                    _lint_hot_loop(self.lint, node, fn.qualname)
        # module-level training scripts (examples, quick experiments)
        stack: List[ast.AST] = list(tree.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.For) and _loader_like(node.iter):
                _lint_hot_loop(self.lint, node, None)
            stack.extend(ast.iter_child_nodes(node))


#: APIs whose failures surface as WorkerError (or carry one): a trivial
#: broad except around these is the anti-pattern RLT401 names. The
#: distinctive names match anywhere; the GENERIC ones (`launch`,
#: `supervise` — plenty of unrelated code has an `app.launch()`) match
#: only when the file imports them from this package.
_WORKER_API_NAMES: Set[str] = {
    "WorkerGroup", "WorkerError", "fit_distributed", "run_distributed",
    "validate_distributed", "test_distributed", "predict_distributed",
    "launch_cpu_spmd", "fit_supervised",
}

_WORKER_API_GENERIC: Set[str] = {"launch", "supervise"}

#: group-handle methods: `<something>group.run(...)` etc.
_WORKER_GROUP_METHODS: Set[str] = {"run", "run_single", "wait", "start"}


def _is_trivial_handler_body(body: List[ast.stmt]) -> bool:
    """Only pass/continue/`...` — the failure vanishes without a trace."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)
                and stmt.value.value is Ellipsis):
            continue
        return False
    return bool(body)


def _handler_swallows(handler: ast.ExceptHandler) -> Optional[str]:
    """The caught-type description when this handler is broad enough to
    eat a WorkerError (bare, Exception/BaseException, or WorkerError
    itself — directly or inside a tuple), else None."""
    t = handler.type
    if t is None:
        return "bare except:"
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for el in types:
        name = _dotted(el)
        base = name.split(".")[-1] if name else None
        if base in ("Exception", "BaseException", "WorkerError"):
            return f"except {base}"
    return None


def _mentions_worker_api(nodes: List[ast.stmt],
                         known: Set[str]) -> Optional[str]:
    """A worker-API name used inside these statements, else None."""
    for stmt in nodes:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and node.id in known:
                return node.id
            if isinstance(node, ast.Attribute):
                if node.attr in known:
                    return node.attr
                if (node.attr in _WORKER_GROUP_METHODS
                        and isinstance(node.value, ast.Name)
                        and "group" in node.value.id.lower()):
                    return f"{node.value.id}.{node.attr}"
    return None


class _ResilienceLint(ast.NodeVisitor):
    """RLT401: the two code shapes that defeat supervision.

    (a) a bare/broad ``except`` with a trivial body (pass/continue/...)
        wrapped around worker-group APIs — the WorkerError carrying the
        dead rank's classification and log tail evaporates;
    (b) a ``WorkerGroup`` that is started but has no ``shutdown()``
        reachable from a ``finally`` and is not managed by ``with`` —
        the failure path leaks live worker processes (and on a pod,
        their hosts' chips). Groups handed away (returned, stored on
        self) are the caller's responsibility and are not flagged.
    """

    def __init__(self, lint: "_FileLint"):
        self.lint = lint
        self._known = set(_WORKER_API_NAMES)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        # the generic names ('launch', 'supervise') become worker APIs
        # only with import evidence — an unrelated app.launch() must
        # never trip the rule
        if node.module and node.module.startswith("ray_lightning_tpu"):
            for alias in node.names:
                if alias.name in _WORKER_API_GENERIC:
                    self._known.add(alias.asname or alias.name)
        self.generic_visit(node)

    def visit_Try(self, node: ast.Try):
        for handler in node.handlers:
            caught = _handler_swallows(handler)
            if caught is None or not _is_trivial_handler_body(handler.body):
                continue
            api = _mentions_worker_api(node.body, self._known)
            if api is not None:
                self.lint.add(
                    "RLT401",
                    f"{caught} with a pass-only body swallows failures "
                    f"from {api}() — a dead worker's WorkerError (rank, "
                    "cause, log tail) vanishes and the run reads as "
                    "success; let it propagate to the supervisor, or "
                    "handle and re-raise",
                    handler)
        self.generic_visit(node)

    @staticmethod
    def _walk_scope(body: List[ast.stmt]):
        """Every node under these statements EXCLUDING nested function
        bodies (each def is its own ownership scope)."""
        stack: List[ast.AST] = list(body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _scan_scope(self, body: List[ast.stmt]) -> None:
        """One function scope (nested defs are their own scopes)."""
        assigns: List[Tuple[str, ast.Call]] = []
        started: Set[str] = set()
        shutdown_in_finally: Set[str] = set()
        with_managed: Set[str] = set()
        escaped: Set[str] = set()  # returned/yielded: ownership left
        for node in self._walk_scope(body):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                call = node.value
                chained_start = False
                if (isinstance(call.func, ast.Attribute)
                        and call.func.attr == "start"
                        and isinstance(call.func.value, ast.Call)):
                    # g = WorkerGroup(...).start()
                    call = call.func.value
                    chained_start = True
                callee = _dotted(call.func) or ""
                if callee.split(".")[-1] == "WorkerGroup":
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            assigns.append((tgt.id, call))
                            if chained_start:
                                started.add(tgt.id)
                        # self.group = WorkerGroup(...): lifecycle is
                        # managed elsewhere on the object — not flagged
            elif isinstance(node, ast.With):
                for item in node.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Name):
                        with_managed.add(ctx.id)
            elif isinstance(node, (ast.Return, ast.Yield)) and isinstance(
                    getattr(node, "value", None), ast.Name):
                escaped.add(node.value.id)
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.attr == "start"):
                started.add(node.func.value.id)
            elif isinstance(node, ast.Try) and node.finalbody:
                for fin_node in self._walk_scope(node.finalbody):
                    if (isinstance(fin_node, ast.Call)
                            and isinstance(fin_node.func, ast.Attribute)
                            and fin_node.func.attr == "shutdown"
                            and isinstance(fin_node.func.value, ast.Name)):
                        shutdown_in_finally.add(fin_node.func.value.id)
        for name, call in assigns:
            if name in with_managed or name in escaped:
                continue
            if name not in started:
                continue  # never started: nothing leaked yet
            if name in shutdown_in_finally:
                continue
            self.lint.add(
                "RLT401",
                f"WorkerGroup {name!r} is start()ed with no "
                f"{name}.shutdown() in a finally and no `with` block — "
                "a failure between start and teardown leaks the worker "
                "processes (on a pod: their hosts' chips). Use `with "
                "WorkerGroup(...) as g:` or try/finally",
                call)

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._scan_scope(node.body)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._scan_scope(node.body)
        self.generic_visit(node)

    def visit_Module(self, node: ast.Module):
        self._scan_scope(node.body)
        self.generic_visit(node)


# ---- RLT501 arm B: unbounded event accumulation in callback code ----------

#: the hooks that run once per batch — an unbounded append here grows
#: for the life of the run
_BATCH_HOOKS: Tuple[str, ...] = (
    "on_train_batch_start", "on_train_batch_end",
)


def _self_attr(node: ast.AST) -> Optional[str]:
    """'X' for ``self.X`` (through a subscript: ``self.X[0]``)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _TelemetryCallbackLint:
    """RLT501 arm B: ``self.X.append(...)`` in a per-batch Callback hook
    where nothing in the class ever bounds X — no deque(maxlen=...)
    construction, no reassignment/truncation outside __init__, no
    clear/pop. The sanctioned shapes (ThroughputMonitor's
    ``self._times = self._times[-window:]``, a ring deque, an explicit
    flush-and-clear) all leave bounding evidence the scan accepts."""

    def __init__(self, lint: _FileLint):
        self.lint = lint

    @staticmethod
    def _is_callback(cls: ast.ClassDef) -> bool:
        for base in cls.bases:
            name = _dotted(base) or ""
            if name.split(".")[-1].endswith("Callback"):
                return True
        return False

    def run(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and self._is_callback(node):
                self._scan_class(node)

    def _scan_class(self, cls: ast.ClassDef) -> None:
        bounded: Set[str] = set()
        appends: List[Tuple[str, ast.Call]] = []
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(item):
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        attr = _self_attr(tgt)
                        if attr is None:
                            continue
                        if item.name != "__init__":
                            # truncation / replacement in a hook body
                            bounded.add(attr)
                        elif (isinstance(node.value, ast.Call)
                              and (_dotted(node.value.func) or ""
                                   ).split(".")[-1] == "deque"):
                            bounded.add(attr)  # ring from birth
                elif isinstance(node, ast.Delete):
                    for tgt in node.targets:
                        attr = _self_attr(tgt)
                        if attr is not None:
                            bounded.add(attr)
                elif (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    attr = _self_attr(node.func.value)
                    if attr is None:
                        continue
                    if node.func.attr in ("clear", "pop", "popleft"):
                        bounded.add(attr)
                    elif (node.func.attr == "append"
                            and item.name in _BATCH_HOOKS):
                        appends.append((attr, node))
        for attr, call in appends:
            if attr in bounded:
                continue
            self.lint.add(
                "RLT501",
                f"self.{attr}.append(...) in a per-batch callback hook "
                f"with no bound in class {cls.name!r} (no deque(maxlen), "
                "no truncation/clear/pop anywhere) — the list grows for "
                "the life of the run; buffer in a bounded ring "
                "(collections.deque(maxlen=N) or truncate on append) "
                "and flush on a cadence (docs/OBSERVABILITY.md)",
                call, cls.name)


# ---- RLT502: serve-loop recompile -----------------------------------------
#
# The classic serving trap: a decode loop that calls a jitted function
# with a Python-varying shape — a sequence buffer grown by concatenate
# every iteration, or a prompt sliced to its un-bucketed length — so
# EVERY request (or every token) silently retraces and recompiles. The
# rule is deliberately narrow: the callee must be jit-wrapped in this
# file, and the argument must provably change shape across iterations
# of the enclosing loop.

#: growth constructors: `x = <ns>.concatenate([x, ...])` and friends
#: rebind x to a longer buffer every trip
_RLT502_GROWERS: Set[str] = {
    "concatenate", "append", "hstack", "vstack", "column_stack",
    "stack", "r_", "pad",
}


def _rlt502_is_jit_expr(expr: ast.AST) -> bool:
    """jax.jit / jit / partial(jax.jit, ...) (as decorator or call)."""
    name = _dotted(expr)
    if name is not None:
        return name.split(".")[-1] == "jit"
    if isinstance(expr, ast.Call):
        fname = _dotted(expr.func) or ""
        if fname.split(".")[-1] == "partial" and expr.args:
            return _rlt502_is_jit_expr(expr.args[0])
        return _rlt502_is_jit_expr(expr.func)
    return False


def _rlt502_jitted_names(tree: ast.Module) -> Set[str]:
    """Local names known to be jit-compiled callables: decorated defs
    and `name = jax.jit(...)`-style assignments."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_rlt502_is_jit_expr(d) for d in node.decorator_list):
                names.add(node.name)
        elif isinstance(node, ast.Assign) and isinstance(node.value,
                                                        ast.Call):
            if (_rlt502_is_jit_expr(node.value.func)
                    or _rlt502_is_jit_expr(node.value)):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


def _rlt502_own_loop_nodes(loop: ast.AST) -> Iterable[ast.AST]:
    """One loop's body nodes, excluding nested defs AND nested loops
    (each nested loop is linted as its own loop)."""
    stack: List[ast.AST] = list(loop.body) + list(
        getattr(loop, "orelse", []))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.For, ast.While)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _rlt502_growing_names(own: List[ast.AST]) -> Set[str]:
    """Names rebound inside the loop from a concatenate/append/... of
    THEMSELVES — a buffer that grows every iteration."""
    grow: Set[str] = set()
    for node in own:
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        callee = (_dotted(node.value.func) or "").split(".")[-1]
        if callee not in _RLT502_GROWERS:
            continue
        used = {n.id for n in ast.walk(node.value)
                if isinstance(n, ast.Name)}
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id in used:
                grow.add(t.id)
    return grow


def _rlt502_varying_names(loop: ast.AST, own: List[ast.AST]) -> Set[str]:
    """Names whose VALUE changes per iteration: the for target plus
    anything (re)assigned in the loop body."""
    vary: Set[str] = set()
    if isinstance(loop, ast.For):
        vary |= {n.id for n in ast.walk(loop.target)
                 if isinstance(n, ast.Name)}
    for node in own:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        vary.add(n.id)
        elif isinstance(node, ast.AugAssign) and isinstance(node.target,
                                                            ast.Name):
            vary.add(node.target.id)
    return vary


def _rlt502_varying_slice(arg: ast.AST, vary: Set[str]) -> Optional[str]:
    """A slice bound inside ``arg`` that references a loop-varying name
    (``x[:t]`` — shape changes per trip). Integer INDEXING (``x[t]``)
    keeps the shape constant and never fires."""
    for node in ast.walk(arg):
        if not isinstance(node, ast.Subscript):
            continue
        slices = (node.slice.elts if isinstance(node.slice, ast.Tuple)
                  else [node.slice])
        for sl in slices:
            if not isinstance(sl, ast.Slice):
                continue
            for bound in (sl.lower, sl.upper):
                if bound is None:
                    continue
                for n in ast.walk(bound):
                    if isinstance(n, ast.Name) and n.id in vary:
                        return n.id
    return None


class _ServeLoopLint:
    """RLT502 driver: every for/while loop in NON-traced code (a loop
    under a tracer has static shapes by construction) that calls a
    known-jitted function with a per-iteration-varying shape."""

    def __init__(self, lint: _FileLint):
        self.lint = lint

    def _lint_loop(self, loop: ast.AST, jitted: Set[str],
                   symbol: Optional[str],
                   outer_vary: Set[str]) -> None:
        own = list(_rlt502_own_loop_nodes(loop))
        grow = _rlt502_growing_names(own)
        # an ENCLOSING loop's targets vary per iteration here too: the
        # canonical per-request-outer / per-token-inner serve loop
        # slices by the outer loop's un-bucketed length
        # (`for l in lens: while ...: step(params, toks[:, :l])`)
        vary = _rlt502_varying_names(loop, own) | outer_vary
        for node in own:
            if not isinstance(node, ast.Call):
                continue
            if not (isinstance(node.func, ast.Name)
                    and node.func.id in jitted):
                continue
            for arg in list(node.args) + [kw.value for kw in
                                          node.keywords]:
                if isinstance(arg, ast.Name) and arg.id in grow:
                    detail = (f"{arg.id!r} is grown by concatenate/"
                              "append inside the loop")
                elif (sliced := _rlt502_varying_slice(arg, vary)) \
                        is not None:
                    detail = (f"sliced by loop-varying {sliced!r}")
                else:
                    continue
                self.lint.add(
                    "RLT502",
                    f"jitted {node.func.id}() is called in this loop "
                    f"with an argument whose shape changes every "
                    f"iteration ({detail}): each call silently "
                    "retraces AND recompiles — the classic serve-loop "
                    "trap (growing sequence axis / un-bucketed prompt "
                    "lengths). Keep device shapes fixed: decode into a "
                    "position-indexed KV cache (models.llama.generate), "
                    "pad prompts to a bucket, or serve through the "
                    "fixed-capacity slot engine (serve.DecodeEngine, "
                    "docs/SERVING.md)", node, symbol)
                break

    def run(self, tree: ast.Module, funcs: List["_Func"]) -> None:
        jitted = _rlt502_jitted_names(tree)
        if not jitted:
            return
        traced_nodes = {id(fn.node) for fn in funcs if fn.traced}

        def walk(stmts, symbol, outer_vary: Set[str]):
            for node in stmts:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    if id(node) not in traced_nodes:
                        walk(node.body, node.name, set())
                    continue
                if isinstance(node, ast.Lambda):
                    continue
                if isinstance(node, (ast.For, ast.While)):
                    self._lint_loop(node, jitted, symbol, outer_vary)
                    inner_vary = outer_vary | _rlt502_varying_names(
                        node, list(_rlt502_own_loop_nodes(node)))
                    walk(list(node.body) + list(node.orelse), symbol,
                         inner_vary)
                    continue
                walk(list(ast.iter_child_nodes(node)), symbol,
                     outer_vary)

        walk(tree.body, None, set())


class _PinnedWorldLint:
    """RLT601 pinned-world-size (docs/ELASTIC.md): code that computes
    per-host batch or rank math from a HARDCODED device count instead
    of the mesh/plan helpers breaks the moment the elastic supervisor
    reshards the job onto a different world size. Two arms:

      A ``jax.device_count() == 8`` / ``len(jax.devices()) != 4`` —
        an ==/!= pin of a topology query against a literal >= 2
        (capability checks ``== 1`` / ``> 1`` are fine and common);
      B ``batch // 8`` / ``global_batch % 16`` / ``rank // 4`` — batch/
        world/rank-named values floor-divided or modulo'd by a literal
        power-of-two >= 2 (the device-count constants jobs get pinned
        to). Deriving the divisor from the mesh
        (``mesh.batch_size_divisor``, ``plan.dp_degree``) never fires:
        those are names/calls, not literals.
    """

    #: terminal attribute names of the topology queries arm A watches
    _COUNT_CALLS = ("device_count", "local_device_count",
                    "process_count", "global_device_count")
    _NAME_RE = re.compile(r"(?:^|_)(batch|bsz|world|rank)(?:_|$|size)",
                          re.IGNORECASE)

    def __init__(self, lint: _FileLint):
        self.lint = lint

    def run(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Compare):
                self._compare(node)
            elif (isinstance(node, ast.BinOp)
                    and isinstance(node.op, (ast.FloorDiv, ast.Mod))):
                self._divmod(node)

    def _is_count_call(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        name = (_dotted(node.func) or "").split(".")[-1]
        if name in self._COUNT_CALLS:
            return True
        if name == "len" and node.args:
            inner = node.args[0]
            if isinstance(inner, ast.Call):
                iname = (_dotted(inner.func) or "").split(".")[-1]
                return iname in ("devices", "local_devices")
        return False

    def _compare(self, node: ast.Compare) -> None:
        sides = [node.left] + list(node.comparators)
        for op, lhs, rhs in zip(node.ops, sides, sides[1:]):
            if not isinstance(op, (ast.Eq, ast.NotEq)):
                continue
            for call, lit in ((lhs, rhs), (rhs, lhs)):
                if (self._is_count_call(call)
                        and isinstance(lit, ast.Constant)
                        and isinstance(lit.value, int)
                        and lit.value >= 2):
                    self.lint.add(
                        "RLT601",
                        f"topology query pinned to a hardcoded world "
                        f"size ({lit.value}): this code dies on any "
                        "other topology, so the elastic supervisor can "
                        "never reshard the job (docs/ELASTIC.md). Gate "
                        "on capability (> 1) or derive the expectation "
                        "from the mesh/plan (MeshSpec.resolve, "
                        "plan.dp_degree)", node)
                    return

    def _divmod(self, node: ast.BinOp) -> None:
        rhs = node.right
        if not (isinstance(rhs, ast.Constant)
                and isinstance(rhs.value, int)):
            return
        v = rhs.value
        if v < 2 or (v & (v - 1)):  # literal power-of-two >= 2 only
            return
        name = _dotted(node.left)
        if name is None and isinstance(node.left, ast.Subscript):
            name = _dotted(node.left.value)
        if name is None or not self._NAME_RE.search(
                name.split(".")[-1]):
            return
        op = "//" if isinstance(node.op, ast.FloorDiv) else "%"
        self.lint.add(
            "RLT601",
            f"per-host batch/rank math against a hardcoded device "
            f"count ({name} {op} {v}): the divisor is pinned to one "
            "world size, so an elastic reshard (or any other topology) "
            "silently mis-shards. Derive it from the mesh "
            "(parallel.mesh.batch_size_divisor(mesh), plan.dp_degree) "
            "— docs/ELASTIC.md", node)


# ---- RLT503: unbounded ledger reads on cadence-polled paths ---------------

#: terminal names of the evidence-ledger readers that parse a whole
#: growing *.jsonl (or a directory of them) per call; every one accepts
#: a tail/window bound its cadence-polled callers must thread
#: (telemetry/spans.py ledger_tail_lines is the shared substrate)
_LEDGER_READERS: Set[str] = {
    "read_spans", "read_metrics", "read_all_metrics",
    "newest_metrics_per_replica", "aggregate_metrics_dir",
    "load_signal_from_dir", "load_signal", "read_ledger",
    "read_ledgers", "read_incidents", "load_timeline",
    "load_timeline_events",
}

#: kwargs whose presence (with anything but a literal None) marks the
#: call bounded. A threaded VARIABLE counts — the caller owns the
#: bound; the defect this rule hunts is the reader given no bound at
#: all on a polled path.
_LEDGER_BOUND_KWARGS: Set[str] = {
    "tail_bytes", "max_bytes", "window", "limit", "last_n",
}


def _rlt503_bounded(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg in _LEDGER_BOUND_KWARGS:
            if isinstance(kw.value, ast.Constant) \
                    and kw.value.value is None:
                continue
            return True
    return False


def _rlt503_loop_nodes(loop: ast.AST) -> Iterable[ast.AST]:
    """One loop's body nodes, nested loops included, nested function
    defs excluded (they run on their own schedule, not per poll)."""
    stack: List[ast.AST] = list(loop.body) + list(
        getattr(loop, "orelse", ()) or ())
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _rlt503_is_sleep(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = _dotted(node.func) or ""
    return name.split(".")[-1] == "sleep"


class _LedgerTailLint:
    """RLT503 unbounded-ledger-read (docs/OBSERVABILITY.md): a
    cadence-polled code path — a loop that sleeps between iterations
    (`monitor --follow`, a controller poll loop), or any same-file
    function reachable from one — parses an entire evidence ledger
    into memory every poll. The appended-forever ledgers make that a
    cost that grows with run age; every reader takes a tail/window
    bound, and threading one (even as a variable) sanctions the call.
    Reachability follows the same same-file call edges the traced-set
    fixpoint uses, so a reader two helpers below the follow loop still
    fires."""

    def __init__(self, lint: _FileLint):
        self.lint = lint
        self._reported: Set[int] = set()

    def _lint_call(self, node: ast.AST,
                   symbol: Optional[str]) -> None:
        if not isinstance(node, ast.Call) or id(node) in self._reported:
            return
        name = (_dotted(node.func) or "").split(".")[-1]
        if name not in _LEDGER_READERS or _rlt503_bounded(node):
            return
        self._reported.add(id(node))
        self.lint.add(
            "RLT503",
            f"{name}() parses its whole ledger on a cadence-polled "
            "path with no tail/window bound: the *.jsonl evidence "
            "ledgers are append-only and grow for the life of the "
            "run, so every poll re-parses all of history and the "
            "live view's cost grows without bound. Thread a bound "
            "(tail_bytes= / window= — the readers keep the "
            "clock-alignment header and the newest entries, which is "
            "all a live view needs; docs/OBSERVABILITY.md "
            "'unified timeline')", node, symbol)

    def run(self, tree: ast.Module, coll: "_Collector") -> None:
        polled: Set[int] = set()
        fn_of_id = {id(fn.node): fn for fn in coll.funcs}

        def _seed_from_loop(loop: ast.AST, cls: Optional[str],
                            symbol: Optional[str]) -> None:
            nodes = list(_rlt503_loop_nodes(loop))
            if not any(_rlt503_is_sleep(n) for n in nodes):
                return
            for n in nodes:
                self._lint_call(n, symbol)
                if not isinstance(n, ast.Call):
                    continue
                if isinstance(n.func, ast.Name):
                    for callee in coll.by_name.get(n.func.id, ()):
                        if callee.cls is None and callee.parent is None:
                            polled.add(id(callee.node))
                elif (isinstance(n.func, ast.Attribute)
                        and isinstance(n.func.value, ast.Name)
                        and n.func.value.id == "self"
                        and cls is not None):
                    callee = coll.by_method.get((cls, n.func.attr))
                    if callee is not None:
                        polled.add(id(callee.node))

        for fn in coll.funcs:
            for node in _own_nodes(fn.node):
                if isinstance(node, (ast.While, ast.For)):
                    _seed_from_loop(node, fn.cls, fn.qualname)
        # module-level polling scripts
        stack: List[ast.AST] = list(tree.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, (ast.While, ast.For)):
                _seed_from_loop(node, None, None)
                continue
            stack.extend(ast.iter_child_nodes(node))

        # propagate polled-ness along same-file call edges (the traced
        # fixpoint's resolution rules)
        changed = True
        while changed:
            changed = False
            for fn in coll.funcs:
                if id(fn.node) not in polled:
                    continue
                for kind, name in fn.calls:
                    if kind == "self" and fn.cls is not None:
                        callee = coll.by_method.get((fn.cls, name))
                        if callee is not None and \
                                id(callee.node) not in polled:
                            polled.add(id(callee.node))
                            changed = True
                    elif kind == "name":
                        for callee in coll.by_name.get(name, ()):
                            if callee.cls is None \
                                    and callee.parent is None \
                                    and id(callee.node) not in polled:
                                polled.add(id(callee.node))
                                changed = True
        for node_id in polled:
            fn = fn_of_id.get(node_id)
            if fn is None:
                continue
            for node in _own_nodes(fn.node):
                self._lint_call(node, fn.qualname)


# ---- RLT504: per-token channel chatter ------------------------------------

#: iteration sources that are one TICK's emitted tokens — the engine
#: returns them as a batch, so anything looping them is per-token
_RLT504_EMISSIONS_RE = re.compile(
    r"(?:^|_)(emissions|emitted|toks|tokens)(?:_|$)", re.IGNORECASE)
#: channel-shaped receivers: the request channel's writer/reader, the
#: worker side-channel queue, or anything named like one
_RLT504_RECEIVER_RE = re.compile(
    r"(?:^|_)(queue|channel|chan|writer|reader|sock|conn|pipe)"
    r"(?:_|$|\d)", re.IGNORECASE)
#: send/recv verbs that cost a syscall (+fsync on the command log) each
_RLT504_VERBS = {"send", "put", "put_nowait", "recv", "poll",
                 "send_bytes", "recv_bytes"}


class _ChannelChatterLint:
    """RLT504 per-token-channel-chatter (docs/SERVING.md "the request
    channel"): a serving worker's per-tick loop over the engine's
    emitted tokens doing an UNBATCHED channel operation per element.
    The engine tick already amortized the device work into one call; a
    per-token queue put / channel send / reader poll reintroduces a
    syscall (and on the command log an fsync) per TOKEN, so the wire
    chatter scales with tokens/tick instead of ticks and the worker
    loop stalls on I/O between emissions. The batched discipline —
    accumulate the tick's emissions, ONE side-channel item per
    iteration, ONE highest-seq ack per poll batch
    (serve/driver.py `_replica_session_main`) — never fires: its
    sends sit outside the per-token loop."""

    def __init__(self, lint: _FileLint):
        self.lint = lint

    @staticmethod
    def _emissions_name(it: ast.AST) -> Optional[str]:
        """Terminal name in the loop's iterable that reads as a token
        batch (`last_emissions`, `emitted`, `toks`) — looks through
        zip()/enumerate()/attribute chains."""
        for node in ast.walk(it):
            name = None
            if isinstance(node, ast.Name):
                name = node.id
            elif isinstance(node, ast.Attribute):
                name = node.attr
            if name and _RLT504_EMISSIONS_RE.search(name):
                return name
        return None

    def _lint_loop(self, loop: ast.For,
                   symbol: Optional[str]) -> None:
        src = self._emissions_name(loop.iter)
        if src is None:
            return
        for node in _rlt503_loop_nodes(loop):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _RLT504_VERBS):
                continue
            recv = (_dotted(node.func.value) or "").split(".")[-1]
            if not _RLT504_RECEIVER_RE.search(recv):
                continue
            self.lint.add(
                "RLT504",
                f"{recv}.{node.func.attr}() runs once per element of "
                f"{src!r} — an unbatched channel operation per emitted "
                "TOKEN: each pays a syscall (+fsync on the command "
                "log) and a receiver wakeup, so wire chatter scales "
                "with tokens/tick instead of ticks and the decode "
                "loop stalls on I/O the engine tick already "
                "amortized. Batch the tick's emissions into ONE "
                "side-channel item and ack ONE highest-seq per poll "
                "batch (serve/channel.py, docs/SERVING.md 'the "
                "request channel')", node, symbol)

    def run(self, tree: ast.Module, funcs: List["_Func"]) -> None:
        traced_nodes = {id(fn.node) for fn in funcs if fn.traced}

        def walk(stmts, symbol):
            for node in stmts:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    # a traced loop has no channel to chatter on —
                    # same scope rule as the other serve-loop lints
                    if id(node) not in traced_nodes:
                        walk(node.body, node.name)
                    continue
                if isinstance(node, ast.Lambda):
                    continue
                if isinstance(node, ast.For):
                    self._lint_loop(node, symbol)
                walk(list(ast.iter_child_nodes(node)), symbol)

        walk(tree.body, None)


# ---- RLT309: redundant prefix prefill -------------------------------------

#: serving submission verbs — one request enqueued per call
_RLT309_SUBMIT_VERBS = {"submit", "enqueue"}
#: prompt-concatenation spellings (np/jnp.concatenate + friends)
_RLT309_CONCAT = {"concatenate", "concat", "hstack"}


class _PrefixPrefillLint:
    """RLT309 redundant-prefix-prefill (docs/SERVING.md "prefix
    cache"): a serve-side loop submitting one request per iteration
    whose prompt PREPENDS a loop-invariant prefix — the shared system
    prompt — while the file never arms ``prefix_cache=True``. Every
    request then re-prefills the identical prefix tokens and holds its
    own pool copy of them; the scheduler's prefix cache prefills the
    common prefix ONCE and maps the full blocks into each table at
    refcount (`serve/kv_cache.py PrefixCache`, copy-on-write on
    divergence). Any ``prefix_cache=True`` keyword in the file
    sanctions it — the cache is armed, the loop is the intended
    usage."""

    def __init__(self, lint: _FileLint):
        self.lint = lint

    @staticmethod
    def _armed(tree: ast.Module) -> bool:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            for kw in node.keywords:
                if (kw.arg == "prefix_cache"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True):
                    return True
        return False

    @staticmethod
    def _const_prefix(expr: ast.AST,
                      variant: Set[str]) -> Optional[str]:
        """The loop-invariant Name a prompt expression PREPENDS, or
        None. Covers ``np.concatenate([sys, tail])`` (list/tuple or
        vararg form) and ``sys + tail``."""
        if isinstance(expr, ast.Call):
            fname = (_dotted(expr.func) or "").split(".")[-1]
            if fname in _RLT309_CONCAT and expr.args:
                seq = expr.args[0]
                first = (seq.elts[0]
                         if isinstance(seq, (ast.List, ast.Tuple))
                         and seq.elts else seq)
                if (isinstance(first, ast.Name)
                        and first.id not in variant):
                    return first.id
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            if (isinstance(expr.left, ast.Name)
                    and expr.left.id not in variant):
                return expr.left.id
        return None

    def _lint_loop(self, loop: ast.For,
                   symbol: Optional[str]) -> None:
        variant: Set[str] = {
            n.id for n in ast.walk(loop.target)
            if isinstance(n, ast.Name)}
        assigns: Dict[str, ast.AST] = {}
        nodes = list(_rlt503_loop_nodes(loop))
        for node in nodes:
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            variant.add(n.id)
                if (len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    assigns[node.targets[0].id] = node.value
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        variant.add(n.id)
        for node in nodes:
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _RLT309_SUBMIT_VERBS):
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                for kw in sub.keywords:
                    if kw.arg != "prompt":
                        continue
                    expr = kw.value
                    if (isinstance(expr, ast.Name)
                            and expr.id in assigns):
                        expr = assigns[expr.id]
                    prefix = self._const_prefix(expr, variant)
                    if prefix is None:
                        continue
                    recv = (_dotted(node.func.value)
                            or "").split(".")[-1]
                    self.lint.add(
                        "RLT309",
                        f"{recv}.{node.func.attr}() re-submits the "
                        f"loop-invariant prefix {prefix!r} on every "
                        "request's prompt without prefix_cache=True: "
                        "each request PREFILLS the identical prefix "
                        "again and pins its own pool copy of those "
                        "blocks. Arm the scheduler's prefix cache "
                        "(Scheduler(engine, prefix_cache=True)) — the "
                        "common prefix prefills ONCE and the full "
                        "blocks map into every table by refcount, "
                        "copy-on-write on divergence (serve/"
                        "kv_cache.py, docs/SERVING.md 'prefix cache')",
                        node, symbol)
                    return

    def run(self, tree: ast.Module, funcs: List["_Func"]) -> None:
        if self._armed(tree):
            return
        traced_nodes = {id(fn.node) for fn in funcs if fn.traced}

        def walk(stmts, symbol):
            for node in stmts:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    # traced code has no scheduler to submit to —
                    # same scope rule as the other serve-loop lints
                    if id(node) not in traced_nodes:
                        walk(node.body, node.name)
                    continue
                if isinstance(node, ast.Lambda):
                    continue
                if isinstance(node, ast.For):
                    self._lint_loop(node, symbol)
                walk(list(ast.iter_child_nodes(node)), symbol)

        walk(tree.body, None)


# ---- RLT505: silent request drop ------------------------------------------

#: serving submission verbs — one request enters the system per call
_RLT505_SUBMIT_VERBS = {"submit", "enqueue"}
#: drains whose return value IS the typed record set — discarding it
#: discards the only evidence the request was rejected
_RLT505_DRAINS = {"take_sheds"}
#: record buffers a consumer may clear only after reading
_RLT505_BUFFERS = {"last_sheds", "last_preemptions"}


class _SilentDropLint:
    """RLT505 silent-request-drop (docs/SERVING.md "traffic & SLO
    classes"): serving code that makes a request disappear without a
    typed record. Two shapes:

    * a broad ``except``/``except Exception`` whose body only
      ``pass``/``continue``s wrapped around a `submit()`/`enqueue()`
      call — the request vanishes with no terminal status, no shed
      record, no counter;
    * `take_sheds()` called as a bare expression statement (or a
      ``last_sheds``/``last_preemptions`` buffer ``.clear()``ed) —
      the scheduler produced typed shed/preemption records and the
      caller threw them away, so the stream never gets its terminal
      meta and the client retries blind.

    The graceful-overload contract is explicit degradation: every
    rejected rid ends with a reason + retry-after hint. A consumer
    that intentionally discards (e.g. a lockstep follower whose
    LEADER owns emission) sanctions the line with
    ``# rlt: disable=RLT505``."""

    def __init__(self, lint: _FileLint):
        self.lint = lint

    @staticmethod
    def _swallows(handler: ast.ExceptHandler) -> bool:
        """Broad handler whose body only pass/continue/...-es."""
        t = handler.type
        broad = t is None or (
            isinstance(t, (ast.Name, ast.Attribute))
            and (_dotted(t) or "").split(".")[-1]
            in ("Exception", "BaseException"))
        if not broad:
            return False
        for stmt in handler.body:
            if isinstance(stmt, (ast.Pass, ast.Continue)):
                continue
            if (isinstance(stmt, ast.Expr)
                    and isinstance(stmt.value, ast.Constant)):
                continue
            return False
        return True

    def _lint_try(self, node: ast.Try, symbol: Optional[str]) -> None:
        submits = [
            sub for stmt in node.body for sub in ast.walk(stmt)
            if isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr in _RLT505_SUBMIT_VERBS]
        if not submits:
            return
        for handler in node.handlers:
            if not self._swallows(handler):
                continue
            call = submits[0]
            recv = (_dotted(call.func.value) or "").split(".")[-1]
            self.lint.add(
                "RLT505",
                f"a broad except around {recv}.{call.func.attr}() "
                "swallows the failure with a bare pass — the request "
                "vanishes with no terminal status, no typed shed "
                "record, no counter: the client retries blind and "
                "the loss is invisible to watch/metrics. Record a "
                "terminal outcome (or re-raise); rejection must be "
                "EXPLICIT — a typed record with a retry-after hint "
                "(docs/SERVING.md 'traffic & SLO classes')",
                handler, symbol)

    def _lint_expr(self, node: ast.Expr,
                   symbol: Optional[str]) -> None:
        call = node.value
        if not (isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)):
            return
        verb = call.func.attr
        if verb in _RLT505_DRAINS:
            recv = (_dotted(call.func.value) or "").split(".")[-1]
            self.lint.add(
                "RLT505",
                f"{recv}.{verb}() drained as a bare statement — the "
                "typed shed records (rid, reason, retry_after_s) are "
                "produced and immediately discarded: every shed "
                "stream loses its terminal status and the drop is "
                "silent (docs/SERVING.md 'traffic & SLO classes'). "
                "Turn each record into a terminal outcome on the "
                "stream; an intentional discard (lockstep follower — "
                "the leader owns emission) sanctions the line with "
                "# rlt: disable=RLT505", node, symbol)
            return
        if (verb == "clear" and isinstance(call.func.value,
                                           ast.Attribute)
                and call.func.value.attr in _RLT505_BUFFERS):
            self.lint.add(
                "RLT505",
                f"{call.func.value.attr}.clear() wipes the "
                "scheduler's typed record buffer without reading it "
                "— shed/preemption evidence is destroyed before any "
                "consumer could turn it into terminal stream status "
                "(docs/SERVING.md 'traffic & SLO classes')",
                node, symbol)

    def run(self, tree: ast.Module, funcs: List["_Func"]) -> None:
        traced_nodes = {id(fn.node) for fn in funcs if fn.traced}

        def walk(stmts, symbol):
            for node in stmts:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    # traced code has no scheduler to drop from —
                    # same scope rule as the other serve-loop lints
                    if id(node) not in traced_nodes:
                        walk(node.body, node.name)
                    continue
                if isinstance(node, ast.Lambda):
                    continue
                if isinstance(node, ast.Try):
                    self._lint_try(node, symbol)
                elif isinstance(node, ast.Expr):
                    self._lint_expr(node, symbol)
                walk(list(ast.iter_child_nodes(node)), symbol)

        walk(tree.body, None)


def lint_source(source: str, filename: str = "<string>",
                extra_axes: Sequence[str] = ()) -> List[Finding]:
    """Lint one file's source text. Never imports the target."""
    lint = _FileLint(source, filename, extra_axes)
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as exc:
        lint.add("RLT001", f"does not parse: {exc.msg}",
                 type("_N", (), {"lineno": exc.lineno or 1,
                                 "col_offset": exc.offset or 0})())
        return lint.findings

    coll = _Collector(lint)
    coll.visit(tree)
    res = _ResilienceLint(lint)
    # imports first, regardless of where they sit in the file (a Try
    # above a late import must still see the imported generic names)
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            res.visit_ImportFrom(node)
    res.visit(tree)

    # traced-set fixpoint: containment + same-file call edges
    changed = True
    while changed:
        changed = False
        for fn in coll.funcs:
            if fn.traced:
                continue
            if fn.parent is not None and fn.parent.traced:
                fn.traced = True
                changed = True
                continue
        for fn in coll.funcs:
            if not fn.traced:
                continue
            for kind, name in fn.calls:
                if kind == "self" and fn.cls is not None:
                    callee = coll.by_method.get((fn.cls, name))
                    if callee is not None and not callee.traced:
                        callee.traced = True
                        changed = True
                elif kind == "name":
                    for callee in coll.by_name.get(name, ()):
                        # bare-name calls resolve to module-level defs
                        # only (a method never shadows a global name)
                        if callee.cls is None and callee.parent is None \
                                and not callee.traced:
                            callee.traced = True
                            changed = True

    for fn in coll.funcs:
        if fn.traced:
            _lint_traced_body(lint, fn)
    # RLT304 needs the FINAL traced set: hot-loop rules fire only in
    # non-traced code (a loop under a tracer is RLT201's scope)
    _HotLoopLint(lint).run(tree, coll.funcs)
    _TelemetryCallbackLint(lint).run(tree)
    _ServeLoopLint(lint).run(tree, coll.funcs)
    _PinnedWorldLint(lint).run(tree)
    _LedgerTailLint(lint).run(tree, coll)
    _ChannelChatterLint(lint).run(tree, coll.funcs)
    _PrefixPrefillLint(lint).run(tree, coll.funcs)
    _SilentDropLint(lint).run(tree, coll.funcs)
    return lint.findings


def iter_python_files(targets: Sequence[str]) -> List[str]:
    """Expand files / directories (recursively) to .py paths."""
    out: List[str] = []
    for t in targets:
        if os.path.isdir(t):
            for root, dirs, files in os.walk(t):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        else:
            out.append(t)
    return out


def lint_paths(paths: Sequence[str],
               extra_axes: Sequence[str] = ()) -> List[Finding]:
    """Lint files and/or directories; returns all findings."""
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        with open(path, "r", encoding="utf-8") as fh:
            findings.extend(lint_source(fh.read(), path, extra_axes))
    return findings
