"""tracecheck — jaxpr-level collective & memory auditor for jitted train
steps.

PR 1's shardcheck proves a plan is well-formed in *source and spec*
terms; it cannot see what XLA will actually DO with the jitted step.
tracecheck closes that gap without touching hardware: it traces the
strategy's real train step with `jax.make_jaxpr` over abstractions
(`jax.eval_shape` params over an `AbstractMesh` — runs under
`JAX_PLATFORMS=cpu`), then walks the jaxpr, recursing into
pjit/scan/while/cond/remat/shard_map sub-jaxprs, and reports:

  1. the **collective schedule** — every explicit psum / all_gather /
     reduce_scatter / ppermute / all_to_all (shard_map islands: ring and
     ulysses attention, the GPipe pipeline) PLUS the collectives GSPMD
     must insert to run the auto-sharded regions (FSDP weight gathers,
     gradient reductions), each with axes, payload bytes, and a wire/
     latency estimate from the per-topology cost model
     (analysis/costmodel.py);
  2. **implicit resharding** (RLT301, "RESHARD-IMPLICIT") — sharding
     mismatches that force XLA to move an *activation* (not a planned
     parameter gather) or to reconcile two different mesh axes on the
     same dim: ICI traffic the plan never asked for, with the
     responsible eqn's source line and the originating leaf path;
  3. a **peak-HBM estimate** (liveness over the jaxpr: params + opt
     state + the activation high-water mark, remat-aware because remat2
     bodies free their internals) checked against the topology's chip
     budget (RLT302, "HBM-OVERCOMMIT");
  4. **ring/pipeline schedule checks** (RLT303, "RING-DEADLOCK") —
     ppermute permutations with duplicate sources/destinations or
     out-of-range ranks, full permutations that are not a single cycle
     (two disjoint rings never drain), and collective sequences that
     diverge across `cond` branches (SPMD ranks deadlock).

The sharding propagation is a FIRST-ORDER model of GSPMD, not a
reimplementation: per-var specs flow through elementwise ops,
dot_general, transpose/reshape/broadcast, reductions and control flow;
contractions over co-sharded dims become partial sums resolved as
reduce_scatter when the result is parameter-shaped (ZeRO) and psum
otherwise; axis conflicts are resolved the way GSPMD prefers — gather
the parameter-derived side (that IS the FSDP plan), flag the
activation-derived side. Unknown primitives degrade to unknown
shardings, never to invented findings. Real schedules may beat the
estimate (e.g. XLA can turn a psum into reduce_scatter+all_gather and
overlap it); treat the numbers as a reviewable upper bound, stable
across refactors — the point is the DIFF between two plans, not chip
parity.

Entry points: `audit_step(module, strategy, example_batch,
topology=...)`, `Strategy.audit_step(...)`, `TpuModule.audit_step(...)`,
and the CLI `python -m ray_lightning_tpu trace <example|preset|module:fn>
[--topo v5p-64] [--json]`.
"""
from __future__ import annotations

import dataclasses
import math
import os
from typing import (
    Any, Dict, FrozenSet, List, Mapping, Optional, Sequence, Tuple,
)

from ray_lightning_tpu.analysis.costmodel import (
    Topology, collective_cost, compute_time_us, parse_topology,
)
from ray_lightning_tpu.analysis.findings import Finding
from ray_lightning_tpu.ops.dispatch import OVERLAP_PREFETCH_NAME

__all__ = [
    "CollectiveEvent", "TraceReport", "audit_step", "classify_overlap",
    "trace_step", "check_permutation",
]

#: per-dim mesh axes; None = unknown (propagation gave up — never a
#: finding source)
Spec = Optional[Tuple[FrozenSet[str], ...]]

_ELEMENTWISE = {
    "add", "add_any", "sub", "mul", "div", "rem", "max", "min", "pow",
    "atan2", "and", "or", "xor", "shift_left", "shift_right_logical",
    "shift_right_arithmetic", "nextafter", "eq", "ne", "lt", "le", "gt",
    "ge", "select_n", "clamp",
}
_PASSTHROUGH = {
    "convert_element_type", "copy", "neg", "exp", "exp2", "expm1", "log",
    "log1p", "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh",
    "tanh", "asinh", "acosh", "atanh", "logistic", "sqrt", "rsqrt",
    "cbrt", "integer_pow", "sign", "abs", "floor", "ceil", "round",
    "is_finite", "not", "erf", "erfc", "erf_inv", "real", "imag",
    "stop_gradient", "name", "cumsum", "cumprod",
    "cummax", "cummin", "cumlogsumexp", "nan_to_num", "population_count",
    "clz", "copy_start", "copy_done", "reduce_precision", "square",
    "conj", "bitcast_convert_type",
}
_REDUCE = {"reduce_sum", "reduce_prod", "reduce_max", "reduce_min",
           "reduce_and", "reduce_or", "reduce_xor", "argmax", "argmin"}
#: reductions whose cross-shard completion is a real all-reduce worth
#: charging (boolean/arg reduces move negligible bytes)
_REDUCE_COMM = {"reduce_sum", "reduce_prod", "reduce_max", "reduce_min"}
_COLLECTIVES = {"psum", "pmax", "pmin", "ppermute", "all_gather",
                "reduce_scatter", "all_to_all", "pbroadcast"}
_REPLICATED_SOURCES = {"iota", "rng_bit_generator", "random_seed",
                       "random_wrap", "random_bits", "random_fold_in"}


def _repl(ndim: int) -> Tuple[FrozenSet[str], ...]:
    return tuple(frozenset() for _ in range(ndim))


def _axes_in(spec: Spec) -> FrozenSet[str]:
    if spec is None:
        return frozenset()
    out: FrozenSet[str] = frozenset()
    for s in spec:
        out |= s
    return out


def _spec_of_partition_spec(pspec, ndim: int) -> Tuple[FrozenSet[str], ...]:
    """PartitionSpec-like -> per-dim axis sets, padded to ndim."""
    dims: List[FrozenSet[str]] = []
    for entry in tuple(pspec):
        if entry is None:
            dims.append(frozenset())
        elif isinstance(entry, (tuple, list)):
            dims.append(frozenset(entry))
        else:
            dims.append(frozenset((entry,)))
    while len(dims) < ndim:
        dims.append(frozenset())
    return tuple(dims[:ndim])


@dataclasses.dataclass
class _VarInfo:
    spec: Spec
    param: bool = False          # derived exclusively from param/opt/const
    path: Optional[str] = None   # originating leaf path when single-source
    #: the loop multiplier in effect where this value is DEFINED. A
    #: param gather inside a scan whose operand was born outside it is
    #: loop-invariant — XLA hoists it, so it is charged at born_mult,
    #: not at the loop's trip count (lm_head inside the CE chunk scan:
    #: one gather per step, not one per chunk).
    born_mult: int = 1


@dataclasses.dataclass
class CollectiveEvent:
    """One collective site in the traced step (aggregated over loop trips).

    ``payload_bytes`` follows the cost-model contract (costmodel.py):
    local operand bytes for psum/ppermute/reduce_scatter/all_to_all, the
    per-chip post-gather bytes for all_gather. ``count`` folds in scan
    trip counts; ``wire_bytes``/``time_us`` are count-weighted totals.
    ``implicit`` marks collectives *inferred* from sharding propagation
    (GSPMD will insert them) as opposed to explicit shard_map
    collectives; ``unbounded`` marks sites inside a while-loop whose trip
    count the trace cannot know (counted once).

    Overlap accounting (docs/STATIC_ANALYSIS.md "overlap model"):
    ``prefetchable`` marks collectives whose operand is known ahead of
    its use — ZeRO weight gathers (parameter-derived operands) and the
    grad reduce-scatters matched to a parameter; ``scope`` is the id of
    the enclosing scanned body (None at top level); ``hidden_us`` is the
    share of ``time_us`` the overlap classification proved hideable
    behind that scope's per-trip compute window (0 when the traced
    program carries no prefetch schedule)."""

    kind: str
    axes: Tuple[str, ...]
    payload_bytes: int
    count: int
    wire_bytes: int
    time_us: float
    implicit: bool
    source: str
    param_path: Optional[str] = None
    unbounded: bool = False
    prefetchable: bool = False
    scope: Optional[int] = None
    hidden_us: float = 0.0
    #: bytes each chip puts on DCN (multi-slice topologies only): the
    #: inter-slice stage of a hierarchical collective whose group spans
    #: slices. ``wire_bytes`` stays the ICI tier; ``time_us`` includes
    #: both tiers (costmodel.collective_cost).
    dcn_bytes: int = 0
    #: payload dtype name ("bfloat16"/"float32"/...), when the walk
    #: could see it — numcheck's RLT804 judges gradient reductions over
    #: this field (the GSPMD-inserted grad psum/reduce_scatter exists
    #: only as an event, never as a jaxpr eqn). None on synthetic or
    #: pre-dtype-threading events.
    dtype: Optional[str] = None

    @property
    def exposed_us(self) -> float:
        return max(0.0, self.time_us - self.hidden_us)

    def describe(self) -> str:
        tag = "implicit" if self.implicit else "explicit"
        extra = " trip-count-unknown" if self.unbounded else ""
        if self.hidden_us > 0 and self.time_us > 0:
            extra += f" {self.hidden_us / self.time_us:.0%}-hidden"
        elif self.prefetchable and self.scope is not None:
            extra += " exposed"
        who = f"  <{self.param_path}>" if self.param_path else ""
        dcn = (f" +{_fmt_bytes(self.dcn_bytes).strip()} DCN"
               if self.dcn_bytes else "")
        dt = f" {self.dtype}" if self.dtype else ""
        return (f"{self.kind:<14} axes={','.join(self.axes) or '-'} "
                f"x{self.count:<4} {_fmt_bytes(self.wire_bytes)} wire"
                f"{dcn}{dt} {self.time_us:9.1f} us  [{tag}{extra}] "
                f"{self.source}{who}")


def _pallas_kernel_ident(eqn) -> str:
    """One kernel-fn identity string for a `pallas_call` eqn
    ("_decode_kernel at .../paged_attention.py:76" style) — the SINGLE
    extraction both the step auditor and the serve audit's recursive
    scanner use, so the fingerprint can never drift between them."""
    ident = (eqn.params.get("name_and_src_info")
             or eqn.params.get("name") or "pallas")
    return str(ident)


def _aval_dtype(aval) -> Optional[str]:
    dt = getattr(aval, "dtype", None)
    return str(dt) if dt is not None else None


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:7.2f} {unit}"
        n /= 1024
    return f"{n:.2f} TiB"


@dataclasses.dataclass
class TraceReport:
    """Everything tracecheck proved about one (module, strategy,
    topology) triple. `findings` reuse the shardcheck vocabulary
    (RLT301/302/303) so CLI gates and suppression work unchanged."""

    topology: Topology
    mesh_axes: Dict[str, int]
    collectives: List[CollectiveEvent]
    findings: List[Finding]
    params_bytes_per_device: int
    opt_bytes_per_device: int
    peak_hbm_bytes: int
    hbm_budget_bytes: int
    label: str = ""
    #: the overlap classification (`classify_overlap`): scheduled flag,
    #: hidden/exposed ICI time, per-scope breakdown. None only when
    #: classification was skipped.
    overlap: Optional[Dict[str, Any]] = None
    #: pallas kernel identities the walk met (`_pallas_kernel_ident`)
    #: — the serve audit's "which attention path does this step run"
    #: evidence (empty on pure-XLA programs)
    pallas_kernels: List[str] = dataclasses.field(default_factory=list)
    #: numcheck's precision ledger: per-dtype-class byte itemization
    #: ({"params": {dtype: bytes}, "opt_state": {...},
    #: "activations": {...}, "kv_pool": {...}} — sub-jaxpr scratch is
    #: folded into activations per dtype by the walk's `_sub_by`
    #: threading) plus "loss_widest_dtype", the widest float dtype on
    #: the loss output's provenance path. None when the audit ran with
    #: numerics off.
    precision: Optional[Dict[str, Any]] = None

    @property
    def ici_bytes_per_step(self) -> int:
        return sum(e.wire_bytes for e in self.collectives)

    @property
    def dcn_bytes_per_step(self) -> int:
        """Per-chip bytes on the inter-slice (DCN) tier; 0 on a
        single-slice topology."""
        return sum(e.dcn_bytes for e in self.collectives)

    @property
    def ici_time_us(self) -> float:
        return sum(e.time_us for e in self.collectives)

    @property
    def ici_hidden_us(self) -> float:
        return sum(e.hidden_us for e in self.collectives)

    @property
    def ici_exposed_us(self) -> float:
        return sum(e.exposed_us for e in self.collectives)

    @property
    def overlap_hidden_fraction(self) -> float:
        """Fraction of the PREFETCHABLE collective time (ZeRO weight
        gathers + param-matched grad reduce-scatters) the schedule
        hides behind compute; 0.0 when nothing is prefetchable or no
        overlap schedule is present."""
        pref = sum(e.time_us for e in self.collectives if e.prefetchable)
        if pref <= 0:
            return 0.0
        return sum(e.hidden_us for e in self.collectives
                   if e.prefetchable) / pref

    @property
    def fits(self) -> bool:
        return self.peak_hbm_bytes <= self.hbm_budget_bytes

    def totals_by_kind(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for e in self.collectives:
            t = out.setdefault(e.kind, {"count": 0, "wire_bytes": 0,
                                        "time_us": 0.0})
            t["count"] += e.count
            t["wire_bytes"] += e.wire_bytes
            t["time_us"] += e.time_us
        return out

    def summary(self) -> str:
        gib = 1024**3
        lines = [
            f"tracecheck: {self.label or 'step'} on "
            f"{self.topology.describe()}",
            f"mesh {self.mesh_axes}",
        ]
        if self.collectives:
            lines.append("collective schedule (per train step):")
            for e in sorted(self.collectives, key=lambda e: -e.wire_bytes):
                lines.append("  " + e.describe())
            lines.append(
                f"ICI total: {self.ici_bytes_per_step / gib:.3f} GiB/step "
                f"on the wire, ~{self.ici_time_us / 1e3:.2f} ms serialized "
                f"({self.topology.ici_gbps:.0f} GB/s per chip)")
            if self.topology.n_slices > 1:
                lines.append(
                    f"DCN total: {self.dcn_bytes_per_step / gib:.3f} "
                    f"GiB/step per chip across {self.topology.n_slices} "
                    f"slices ({self.topology.dcn_gbps:.1f} GB/s per "
                    "chip) — inter-slice stage of the crossing "
                    "collectives, itemized above")
            ov = self.overlap or {}
            lines.append(
                f"overlap: {'prefetch schedule detected' if ov.get('scheduled') else 'no prefetch schedule (overlap=off)'}"
                f" — {self.overlap_hidden_fraction:.0%} of prefetchable "
                f"collective time hidden behind compute "
                f"({self.ici_hidden_us / 1e3:.2f} ms hidden, "
                f"{self.ici_exposed_us / 1e3:.2f} ms exposed)")
            for sc in ov.get("per_scope", ()):
                lines.append(
                    f"  scope {sc['source']} x{sc['trips']}: "
                    f"compute {sc['compute_us_per_trip']:.0f} us/trip vs "
                    f"prefetchable comm "
                    f"{sc['prefetch_comm_us_per_trip']:.0f} us/trip -> "
                    f"{sc['hidden_fraction']:.0%} hidden")
        else:
            lines.append("collective schedule: none (single-device or "
                         "fully replicated step)")
        lines.append(
            f"peak HBM estimate: {self.peak_hbm_bytes / gib:.2f} GiB "
            f"per device (params {self.params_bytes_per_device / gib:.2f} "
            f"+ opt {self.opt_bytes_per_device / gib:.2f} + live "
            "intermediates) vs budget "
            f"{self.hbm_budget_bytes / gib:.2f} GiB — "
            f"{'FITS' if self.fits else 'DOES NOT FIT'}")
        if self.precision:
            lines.append("precision ledger (per device):")
            for cls in ("params", "opt_state", "activations", "kv_pool"):
                by = self.precision.get(cls) or {}
                if not by:
                    continue
                parts = " + ".join(
                    f"{dt} {b / gib:.3f} GiB"
                    for dt, b in sorted(by.items(), key=lambda kv: -kv[1]))
                lines.append(f"  {cls:<12}: {parts}")
            widest = self.precision.get("loss_widest_dtype")
            if widest:
                lines.append(f"  loss widest-path dtype: {widest}")
        if self.findings:
            lines.append(f"findings ({len(self.findings)}):")
            lines.extend("  " + f.format() for f in self.findings)
        else:
            lines.append("findings: none")
        return "\n".join(lines)

    def to_dict(self) -> dict:
        return {
            "label": self.label,
            "topology": {
                "name": self.topology.name,
                "device_kind": self.topology.device_kind,
                "n_devices": self.topology.n_devices,
                "ici_gbps": self.topology.ici_gbps,
                "hbm_bytes": self.topology.hbm_bytes,
                "n_slices": self.topology.n_slices,
                "dcn_gbps": self.topology.dcn_gbps,
            },
            "mesh": self.mesh_axes,
            "ici_bytes_per_step": self.ici_bytes_per_step,
            "dcn_bytes_per_step": self.dcn_bytes_per_step,
            "ici_time_us": round(self.ici_time_us, 1),
            "ici_hidden_us": round(self.ici_hidden_us, 1),
            "ici_exposed_us": round(self.ici_exposed_us, 1),
            "overlap_hidden_fraction": round(
                self.overlap_hidden_fraction, 4),
            "overlap": self.overlap,
            "collectives": [
                {"kind": e.kind, "axes": list(e.axes),
                 "payload_bytes": e.payload_bytes, "count": e.count,
                 "wire_bytes": e.wire_bytes, "dcn_bytes": e.dcn_bytes,
                 "time_us": round(e.time_us, 1), "implicit": e.implicit,
                 "source": e.source, "param_path": e.param_path,
                 "unbounded": e.unbounded,
                 "prefetchable": e.prefetchable, "scope": e.scope,
                 "hidden_us": round(e.hidden_us, 1), "dtype": e.dtype}
                for e in sorted(self.collectives,
                                key=lambda e: -e.wire_bytes)
            ],
            "totals_by_kind": self.totals_by_kind(),
            "params_bytes_per_device": self.params_bytes_per_device,
            "opt_bytes_per_device": self.opt_bytes_per_device,
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "hbm_budget_bytes": self.hbm_budget_bytes,
            "fits": self.fits,
            "pallas_kernels": list(self.pallas_kernels),
            "precision": self.precision,
            "findings": [f.to_dict() for f in self.findings],
        }


# --------------------------------------------------------------------------
# permutation checks (RLT303)
# --------------------------------------------------------------------------


def check_permutation(perm: Sequence[Tuple[int, int]], axis_size: int,
                      *, source: str = "<ppermute>") -> List[Finding]:
    """Validate one ppermute schedule. Legal schedules (the ops/ hooks
    `ring_attention.ring_perm` and `pipeline.pipeline_perm` are the two
    canonical producers): unique sources, unique destinations, ranks in
    range, and — when the permutation is FULL — a single cycle. Partial
    permutations (open chains) are legal; two disjoint full cycles mean
    two rings that each wait on traffic the other holds."""
    findings: List[Finding] = []
    perm = [(int(s), int(d)) for s, d in perm]
    srcs = [s for s, _ in perm]
    dsts = [d for _, d in perm]
    bad_rank = sorted({r for r in srcs + dsts
                       if r < 0 or r >= axis_size})
    if bad_rank:
        findings.append(Finding(
            "RLT303",
            f"ppermute names rank(s) {bad_rank} outside the axis "
            f"(size {axis_size}) — the schedule cannot execute",
            file=None, symbol=source))
    dup_s = sorted({s for s in srcs if srcs.count(s) > 1})
    dup_d = sorted({d for d in dsts if dsts.count(d) > 1})
    if dup_s:
        findings.append(Finding(
            "RLT303",
            f"ppermute has duplicate source rank(s) {dup_s}: a rank "
            "cannot send two different payloads on one permute",
            symbol=source))
    if dup_d:
        findings.append(Finding(
            "RLT303",
            f"ppermute has duplicate destination rank(s) {dup_d}: "
            "mismatched send/recv pairing — one recv gets two sends",
            symbol=source))
    if (not bad_rank and not dup_s and not dup_d
            and len(perm) == axis_size and axis_size > 1):
        nxt = dict(perm)
        if set(nxt) == set(range(axis_size)):
            seen, r = set(), 0
            while r not in seen:
                seen.add(r)
                r = nxt[r]
            if len(seen) != axis_size:
                n_cycles = _count_cycles(nxt)
                findings.append(Finding(
                    "RLT303",
                    f"full ppermute permutation over {axis_size} ranks "
                    f"decomposes into {n_cycles} disjoint cycles, not "
                    "one ring — each sub-ring waits forever on data the "
                    "others hold (use ops.ring_attention.ring_perm / "
                    "ops.pipeline.pipeline_perm for the canonical "
                    "schedules)", symbol=source))
    return findings


def _count_cycles(nxt: Dict[int, int]) -> int:
    left, n = set(nxt), 0
    while left:
        n += 1
        r = next(iter(left))
        while r in left:
            left.remove(r)
            r = nxt[r]
    return n


# --------------------------------------------------------------------------
# the jaxpr auditor
# --------------------------------------------------------------------------


class _StepAuditor:
    """Single-use: walk one step jaxpr, accumulate events/findings and a
    liveness peak. Per-device byte accounting throughout: a var's bytes
    are its aval bytes divided by the product of its sharded axis sizes
    (inside shard_map the aval already IS per-shard)."""

    def __init__(self, mesh_sizes: Mapping[str, int], topo: Topology,
                 param_shapes: Mapping[Tuple, Tuple[Spec, str]]):
        self.sizes = {ax: s for ax, s in mesh_sizes.items() if s > 1}
        #: FULL axis sizes (incl. trivial) — the slice-layout math needs
        #: the whole mixed radix, not just the live axes
        self.full_sizes = dict(mesh_sizes)
        self.topo = topo
        self._dcn_span_cache: Dict[Tuple[str, ...], int] = {}
        #: shape -> (spec, path) for param/opt leaves AND their
        #: leading-dim-stripped (scan-stacked) suffixes: the ZeRO
        #: reduce_scatter matcher
        self.param_shapes = dict(param_shapes)
        self._events: Dict[Tuple, CollectiveEvent] = {}
        self._findings: Dict[Tuple, Finding] = {}
        self._quiet = 0          # scan-fixpoint passes record nothing
        self._unbounded = 0      # inside while bodies
        #: overlap accounting: one entry per scanned body (the FINAL,
        #: recording walk), keyed by a fresh id — trips, per-trip
        #: dot_general FLOPs (per-device), source, prefetch marker
        self.scopes: Dict[int, Dict[str, Any]] = {}
        self._scope_stack: List[int] = []
        #: the traced program carries the double-buffer fingerprint
        #: (ops.dispatch.OVERLAP_PREFETCH_NAME name equations)
        self.saw_prefetch_marker = False
        #: every pallas kernel the walk met, by its kernel-fn identity
        #: (`_pallas_kernel_ident`) — surfaced as
        #: `TraceReport.pallas_kernels`, where the serve audit/smoke
        #: read "which attention path does this step run": the same
        #: fingerprint-over-reimplementation discipline as the flash
        #: remat tag
        self.pallas_kernels: List[str] = []
        #: per-dtype byte breakdown of the LAST sub-jaxpr walk, set by
        #: _seed_and_walk and read by the enclosing walk() when it
        #: snapshots a new liveness peak — the plumbing that lets the
        #: precision ledger keep `sum(peak_by) == peak` exact through
        #: nested scan/pjit/cond scratch
        self._sub_by: Dict[str, int] = {}

    # ---- bookkeeping ----------------------------------------------------

    def _canon(self, spec: Spec) -> Spec:
        """Drop mesh axes of size 1: they shard nothing and would only
        manufacture phantom layout conflicts."""
        if spec is None:
            return None
        return tuple(frozenset(ax for ax in s if ax in self.sizes)
                     for s in spec)

    def _div(self, spec: Spec) -> int:
        if spec is None:
            return 1
        return math.prod(self.sizes.get(ax, 1) for ax in _axes_in(spec))

    def _aval_bytes(self, aval, spec: Spec = None) -> int:
        shape = getattr(aval, "shape", None)
        dtype = getattr(aval, "dtype", None)
        if shape is None or dtype is None:
            return 0
        return int(math.prod(shape) or 1) * dtype.itemsize // self._div(spec)

    def _dcn_span(self, axes: Sequence[str]) -> int:
        """Slices the collective group over ``axes`` spans on this
        topology's slice-major layout (1 on single-slice). Also 1 when
        the mesh does not cover the whole deployment (an n_devices
        override smaller than the topology): a sub-deployment mesh
        packs into the fewest slices, so charging cross-slice traffic
        from a tiling the hardware never forces would fabricate DCN
        bytes and RLT306 flags."""
        if self.topo.n_slices <= 1:
            return 1
        if math.prod(self.full_sizes.values()) != self.topo.n_devices:
            return 1
        key = tuple(sorted(axes))
        span = self._dcn_span_cache.get(key)
        if span is None:
            from ray_lightning_tpu.parallel.plan import group_dcn_span

            span = group_dcn_span(key, self.full_sizes,
                                  self.topo.n_slices)
            self._dcn_span_cache[key] = span
        return span

    def record(self, kind: str, payload: int, axes: Sequence[str],
               mult: int, *, implicit: bool, source: str,
               param_path: Optional[str] = None,
               prefetchable: bool = False,
               dtype: Optional[str] = None) -> None:
        if self._quiet or not axes:
            return
        group = {ax: self.sizes.get(ax, 1) for ax in axes}
        if math.prod(group.values()) <= 1:
            return
        cost = collective_cost(kind if kind in (
            "psum", "all_gather", "reduce_scatter", "all_to_all",
            "ppermute") else "psum", payload, group, self.topo,
            dcn_group=self._dcn_span(axes))
        scope = self._scope_stack[-1] if self._scope_stack else None
        key = (kind, tuple(sorted(axes)), payload, source, implicit,
               bool(self._unbounded), scope, prefetchable, dtype)
        ev = self._events.get(key)
        if ev is None:
            self._events[key] = CollectiveEvent(
                kind=kind, axes=tuple(sorted(axes)), payload_bytes=payload,
                count=mult, wire_bytes=cost.wire_bytes * mult,
                time_us=cost.time_us * mult, implicit=implicit,
                source=source, param_path=param_path,
                unbounded=bool(self._unbounded),
                prefetchable=prefetchable, scope=scope,
                dcn_bytes=cost.dcn_bytes * mult, dtype=dtype)
        else:
            ev.count += mult
            ev.wire_bytes += cost.wire_bytes * mult
            ev.time_us += cost.time_us * mult
            ev.dcn_bytes += cost.dcn_bytes * mult

    def flag(self, rule: str, message: str, *, source: str,
             param_path: Optional[str] = None) -> None:
        if self._quiet:
            return
        key = (rule, source, message[:100])
        if key not in self._findings:
            self._findings[key] = Finding(
                rule, f"{message} [at {source}]",
                symbol=param_path or source)

    @property
    def events(self) -> List[CollectiveEvent]:
        return list(self._events.values())

    @property
    def findings(self) -> List[Finding]:
        return list(self._findings.values())

    # ---- env helpers ----------------------------------------------------

    def _info(self, v, env) -> _VarInfo:
        if type(v).__name__ == "Literal" or not hasattr(v, "count"):
            ndim = len(getattr(getattr(v, "aval", None), "shape", ()))
            return _VarInfo(_repl(ndim), param=True)
        got = env.get(v)
        if got is None:
            return _VarInfo(None, param=False)
        return got

    @staticmethod
    def _src(eqn) -> str:
        name = eqn.primitive.name
        try:
            from jax._src import source_info_util

            frame = source_info_util.user_frame(eqn.source_info)
            if frame is not None:
                base = os.path.basename(frame.file_name)
                if base == "tracecheck.py":
                    # the synthetic step wrapper (grads -> tx.update ->
                    # apply_updates): name the phase, not this file
                    return f"{name} @ <train-step optimizer update>"
                return f"{name} @ {base}:{frame.start_line}"
        except Exception:  # noqa: BLE001 — provenance is best-effort
            pass
        return name

    # ---- conflict resolution --------------------------------------------

    def _gather(self, info: _VarInfo, aval, axes: FrozenSet[str],
                mult: int, source: str, *, reason: str) -> None:
        """Model GSPMD's resolution of a layout conflict: all-gather the
        operand along ``axes``. A parameter-derived operand is the
        PLANNED FSDP/ZeRO weight gather — scheduled, not flagged; an
        activation gather is traffic the plan never asked for: RLT301."""
        if not axes:
            return
        if info.param:
            # loop-invariant param gathers are hoisted by XLA
            mult = min(mult, max(1, info.born_mult))
        remaining = (tuple(s - axes for s in info.spec)
                     if info.spec is not None else None)
        payload = self._aval_bytes(aval, remaining)
        self.record("all_gather", payload, sorted(axes), mult,
                    implicit=True, source=source, param_path=info.path,
                    prefetchable=info.param, dtype=_aval_dtype(aval))
        if not info.param:
            self.flag(
                "RLT301",
                f"{reason}: XLA must all-gather an activation "
                f"({_fmt_bytes(payload).strip()} over "
                f"{'x'.join(sorted(axes))}) that the plan never asked "
                "for — a dropped output spec upstream",
                source=source, param_path=info.path)

    def _merge(self, infos: Sequence[_VarInfo], avals, out_aval, mult: int,
               source: str) -> _VarInfo:
        """Elementwise merge. STRICT about ignorance: if any same-rank
        operand's sharding is unknown, the result is unknown — an
        invented spec would cascade into invented collectives. Among
        known operands, the first ACTIVATION operand's layout wins
        (activations stay put; parameters move — ZeRO); other operands'
        conflicting axes are gathered, flagged only when the gathered
        side is itself an activation."""
        out_shape = tuple(getattr(out_aval, "shape", ()))
        out_size = int(math.prod(out_shape) or 1)
        # only FULL-SIZE operands constrain the output layout: an
        # expanded broadcast or a rank-padded norm scale is small and
        # cheap to re-layout, so (like GSPMD's most-tiles heuristic) it
        # never dictates where a 16 GiB tensor lives
        cands = [
            (i, inf) for i, inf in enumerate(infos)
            if len(getattr(avals[i], "shape", ())) == len(out_shape)
            and int(math.prod(getattr(avals[i], "shape", ()) or (1,)))
            == out_size]
        if not cands:
            # pure broadcast combination (outer products, rank-padded
            # scales): small operands don't constrain the layout; if all
            # are known the result is simply replicated
            if all(i.spec is not None for i in infos):
                return _VarInfo(_repl(len(out_shape)),
                                param=all(i.param for i in infos))
            return _VarInfo(None, param=all(i.param for i in infos))
        if any(inf.spec is None or len(inf.spec) != len(out_shape)
               for _, inf in cands):
            return _VarInfo(None, param=all(i.param for i in infos))
        # most tiles win: the most-sharded operand keeps its layout,
        # everyone else reshards toward it
        ref_idx, ref = max(
            cands, key=lambda c: sum(1 for s in c[1].spec if s))
        acc: List[FrozenSet[str]] = list(ref.spec)
        placed: Dict[str, int] = {ax: d for d, s in enumerate(acc)
                                  for ax in s}
        for idx, inf in cands:
            if idx == ref_idx:
                continue
            if (inf.param != ref.param and inf.spec != tuple(acc)
                    and _axes_in(inf.spec) == frozenset(placed)):
                # param storage meeting its own gradient/update with the
                # SAME axes on different dims: XLA reduce-scatters grads
                # straight into the param's layout, so the orientation
                # difference is a tracking artifact (square dgrads match
                # transposed), not a reshard — unify to the param side
                win = inf.spec if inf.param else tuple(acc)
                acc = list(win)
                placed = {ax: d for d, s in enumerate(acc) for ax in s}
                continue
            lose: FrozenSet[str] = frozenset()
            for d, s in enumerate(inf.spec):
                for ax in s:
                    if placed.get(ax) == d:
                        continue
                    if ax in placed or acc[d]:
                        lose |= {ax}            # conflicts with ref layout
                    else:
                        acc[d] = acc[d] | {ax}  # free refinement
                        placed[ax] = d
            if lose:
                self._gather(inf, avals[idx], lose, mult, source,
                             reason="operand layout conflicts with the "
                                    "other operand's sharding")
        spec = tuple(acc)
        # no path propagation through merges: a leaf path on a merged
        # value would mis-attribute downstream events to that leaf
        return _VarInfo(spec, param=all(i.param for i in infos))

    def _param_match(self, shape: Tuple[int, ...],
                     partial: FrozenSet[str]):
        """Find the param/opt leaf a partial-summed value is the gradient
        of: exact shape, or (2-D) the transposed shape — XLA emits
        ``x^T @ dy`` dgrads in whichever orientation fuses best. Returns
        (spec, path) or None; the spec's axes must be reducible (subset
        of ``partial``) for the ZeRO reduce_scatter model to apply."""
        hit = self.param_shapes.get(shape)
        if hit is None and len(shape) == 2:
            rev = self.param_shapes.get(shape[::-1])
            if rev is not None and rev[0] is not None:
                hit = (rev[0][::-1], rev[1])
        if hit is None:
            return None
        mspec, mpath = hit
        if (mspec is not None and len(mspec) == len(shape)
                and _axes_in(mspec) and _axes_in(mspec) <= partial):
            return mspec, mpath
        return None

    def _resolve_partial(self, out_aval, out_spec: List[FrozenSet[str]],
                         partial: FrozenSet[str], mult: int,
                         source: str, path: Optional[str]) -> Spec:
        """A value is partial-summed over ``partial``: GSPMD finishes it
        with reduce_scatter when the result is parameter-shaped (its grad
        lands sharded like the param — ZeRO) and all-reduce otherwise."""
        partial = partial - frozenset(
            ax for s in out_spec for ax in s)  # cannot both shard & reduce
        if not partial:
            return tuple(out_spec)
        shape = tuple(getattr(out_aval, "shape", ()))
        match = self._param_match(shape, partial)
        if match is not None:
            mspec, mpath = match
            payload = self._aval_bytes(out_aval, tuple(out_spec))
            self.record("reduce_scatter", payload, sorted(partial),
                        mult, implicit=True, source=source,
                        param_path=mpath or path, prefetchable=True,
                        dtype=_aval_dtype(out_aval))
            return tuple(s | m for s, m in zip(out_spec, mspec))
        payload = self._aval_bytes(out_aval, tuple(out_spec))
        self.record("psum", payload, sorted(partial), mult,
                    implicit=True, source=source, param_path=path,
                    dtype=_aval_dtype(out_aval))
        return tuple(out_spec)

    # ---- the walk -------------------------------------------------------

    def walk(self, jaxpr, env: Dict, mult: int,
             manual: bool) -> Tuple[int, Dict[str, int]]:
        """Propagate shardings through ``jaxpr`` (env maps Var ->
        _VarInfo; invars must be seeded), record events/findings, and
        return ``(peak, peak_by)``: the liveness peak in per-device
        bytes plus its per-dtype itemization (the precision ledger's
        raw material — ``sum(peak_by.values()) == peak`` by
        construction, with nested sub-jaxpr scratch folded in through
        ``self._sub_by``)."""
        eqns = jaxpr.eqns
        last: Dict[Any, int] = {}
        for i, eqn in enumerate(eqns):
            for v in eqn.invars:
                if hasattr(v, "count"):
                    last[v] = i
        for v in jaxpr.outvars:
            if hasattr(v, "count"):
                last[v] = len(eqns)

        def vb(v) -> int:
            if not hasattr(v, "count") or type(v).__name__ == "DropVar":
                return 0
            info = env.get(v)
            return self._aval_bytes(v.aval, info.spec if info else None)

        def vdt(v) -> str:
            return _aval_dtype(getattr(v, "aval", None)) or "opaque"

        live_by: Dict[str, int] = {}
        for v in {*jaxpr.invars, *jaxpr.constvars}:
            b = vb(v)
            if b:
                live_by[vdt(v)] = live_by.get(vdt(v), 0) + b
        live = sum(live_by.values())
        peak = live
        peak_by = dict(live_by)
        for i, eqn in enumerate(eqns):
            self._sub_by = {}
            try:
                sub_peak = self._process(eqn, env, mult, manual)
            except Exception:  # noqa: BLE001 — propagation must degrade,
                # never abort the audit: unknown structure -> unknown spec
                for v in eqn.outvars:
                    env[v] = _VarInfo(None)
                sub_peak = 0
                self._sub_by = {}
            for v in eqn.outvars:  # values defined HERE are born at the
                info = env.get(v)  # current loop multiplier
                if info is not None:
                    info.born_mult = mult
            out_b = sum(vb(v) for v in eqn.outvars)
            if live + (sub_peak or 0) + out_b > peak:
                peak = live + (sub_peak or 0) + out_b
                peak_by = dict(live_by)
                for v in eqn.outvars:
                    b = vb(v)
                    if b:
                        peak_by[vdt(v)] = peak_by.get(vdt(v), 0) + b
                for dt, b in self._sub_by.items():
                    if b:
                        peak_by[dt] = peak_by.get(dt, 0) + b
            live += out_b
            for v in eqn.outvars:
                b = vb(v)
                if b:
                    live_by[vdt(v)] = live_by.get(vdt(v), 0) + b
            for v in {v for v in eqn.invars if hasattr(v, "count")}:
                if last.get(v) == i:
                    b = vb(v)
                    if b:
                        live -= b
                        live_by[vdt(v)] = live_by.get(vdt(v), 0) - b
        return peak, peak_by

    def _seed_and_walk(self, closed_or_open, outer_invars, env, mult,
                       manual) -> Tuple[int, List[_VarInfo]]:
        """Map outer invar infos onto a sub-jaxpr, walk it, return
        (peak, outvar infos). The inner walk's per-dtype breakdown is
        left on ``self._sub_by`` for the enclosing walk's snapshot."""
        inner = getattr(closed_or_open, "jaxpr", closed_or_open)
        sub_env: Dict = {}
        for iv, ov in zip(inner.invars, outer_invars):
            sub_env[iv] = (ov if isinstance(ov, _VarInfo)
                           else self._info(ov, env))
        for cv in inner.constvars:
            sub_env[cv] = _VarInfo(
                _repl(len(getattr(cv.aval, "shape", ()))), param=True)
        sub_peak, sub_by = self.walk(inner, sub_env, mult, manual)
        self._sub_by = sub_by
        outs = [self._info(v, sub_env) for v in inner.outvars]
        return sub_peak, outs

    # ---- per-primitive handlers -----------------------------------------

    def _process(self, eqn, env, mult, manual) -> int:
        name = eqn.primitive.name
        infos = [self._info(v, env) for v in eqn.invars]
        avals = [getattr(v, "aval", None) for v in eqn.invars]
        out = eqn.outvars
        src = self._src(eqn)
        sub_peak = 0

        if (name == "name"
                and eqn.params.get("name") == OVERLAP_PREFETCH_NAME):
            # the double-buffer fingerprint (ops.dispatch.prefetch_named):
            # this trace runs the overlap schedule. Stamp only during
            # the recording walk (same guard as the FLOP counter): a
            # scan-fixpoint pass runs BEFORE the inner scope is pushed,
            # so stamping there would credit the ENCLOSING scope
            self.saw_prefetch_marker = True
            if self._scope_stack and not self._quiet:
                self.scopes[self._scope_stack[-1]]["marker"] = True

        def set_all(info_list):
            for v, info in zip(out, info_list):
                env[v] = info

        def set_unknown():
            param = all(i.param for i in infos)
            # sound fallback for ANY primitive: replicated in ->
            # replicated out (no mesh axis can appear from nowhere) —
            # keeps pure-const chains (rope tables, masks) propagating
            # through primitives the walker has no rule for
            if infos and all(i.spec is not None and not _axes_in(i.spec)
                             for i in infos):
                set_all([_VarInfo(
                    _repl(len(getattr(v.aval, "shape", ()))), param=param)
                    for v in out])
            else:
                set_all([_VarInfo(None, param=param) for _ in out])

        if name == "optimization_barrier":
            # positional identity: each output mirrors ITS input (the
            # generic passthrough would smear the first operand's spec
            # over every output — for the overlap barrier that would
            # hand the activation a weight layout and invent reshards)
            set_all([dataclasses.replace(i) for i in infos[:len(out)]])
        elif name == "shard_alike":
            # jax.experimental.shard_alike: both outputs leave with the
            # UNIFIED layout. The model adopts the first operand's known
            # spec for both (the overlap path's only use pins each grad
            # leaf to its param shard's layout — losing this to unknown
            # used to charge every stacked layer grad at full size).
            known = next((i for i in infos if i.spec is not None),
                         _VarInfo(None))
            set_all([_VarInfo(known.spec, param=i.param, path=i.path)
                     for i in infos[:len(out)]])
        elif name in _PASSTHROUGH:
            base = next((i for i, a in zip(infos, avals)
                         if a is not None and i.spec is not None
                         and len(i.spec) == len(getattr(
                             out[0].aval, "shape", ()))), None)
            info = base or _VarInfo(None, param=all(i.param for i in infos))
            set_all([dataclasses.replace(info) for _ in out])
        elif name in _ELEMENTWISE:
            merged = self._merge(infos, avals, out[0].aval, mult, src)
            set_all([dataclasses.replace(merged) for _ in out])
        elif name == "dot_general":
            set_all([self._dot_general(eqn, infos, avals, mult, src)])
        elif name in _REDUCE:
            set_all([self._reduce(eqn, infos, avals, mult, src)
                     for _ in out])
        elif name == "transpose":
            perm = eqn.params["permutation"]
            spec = infos[0].spec
            new = (tuple(spec[p] for p in perm)
                   if spec is not None else None)
            set_all([dataclasses.replace(infos[0], spec=new)])
        elif name == "broadcast_in_dim":
            set_all([self._broadcast(eqn, infos[0])])
        elif name == "reshape":
            set_all([self._reshape(eqn, infos[0], avals[0])])
        elif name == "squeeze":
            dims = set(eqn.params["dimensions"])
            spec = infos[0].spec
            new = (tuple(s for d, s in enumerate(spec) if d not in dims)
                   if spec is not None else None)
            set_all([dataclasses.replace(infos[0], spec=new)])
        elif name == "pad":
            spec = infos[0].spec
            if spec is not None:
                cfg = eqn.params["padding_config"]
                new = tuple(s if lo == hi == interior == 0 else frozenset()
                            for s, (lo, hi, interior) in zip(spec, cfg))
                set_all([dataclasses.replace(infos[0], spec=new)])
            else:
                set_unknown()
        elif name == "slice":
            set_all([self._slice(eqn, infos[0], avals[0])])
        elif name in ("dynamic_slice", "dynamic_update_slice"):
            spec = infos[0].spec
            if spec is not None:
                oshape = getattr(out[0].aval, "shape", ())
                ishape = getattr(avals[0], "shape", ())
                new = tuple(
                    s if o == i else frozenset()
                    for s, o, i in zip(spec, oshape, ishape))
                set_all([_VarInfo(new, param=all(x.param for x in infos),
                                  path=infos[0].path)])
            else:
                set_unknown()
        elif name == "concatenate":
            cd = eqn.params["dimension"]
            ondim = len(getattr(out[0].aval, "shape", ()))
            if any(i.spec is None or len(i.spec) != ondim
                   for i in infos):
                set_unknown()
            else:
                # agreement-only: keep axes every piece shards the same
                # way; the concatenated dim itself ends up unsharded
                spec = tuple(
                    frozenset() if d == cd else frozenset.intersection(
                        *(i.spec[d] for i in infos))
                    for d in range(ondim))
                set_all([_VarInfo(spec,
                                  param=all(i.param for i in infos))])
        elif name == "conv_general_dilated":
            # batch passthrough only: the output batch dim keeps the
            # input's sharding; kernel/feature placement and conv-dgrad
            # reductions are not modeled (documented undercount)
            dn = eqn.params["dimension_numbers"]
            lhs = infos[0]
            ondim = len(getattr(out[0].aval, "shape", ()))
            if lhs.spec is None:
                set_unknown()
            else:
                spec = [frozenset()] * ondim
                spec[dn.out_spec[0]] = lhs.spec[dn.lhs_spec[0]]
                set_all([_VarInfo(tuple(spec))])
        elif name == "pallas_call":
            # every kernel in ops/ (flash, rmsnorm, paged_attention) is
            # LOCAL: no cross-device semantics, and each output has the
            # layout of the same-shaped input (flash out = q's sharding,
            # norm out = x's). Unmatched outputs stay unknown. The walk
            # still RECURSES into the kernel jaxpr — for recognition
            # (which kernel runs: the serve audit reads
            # `pallas_kernels`), for its dot_general FLOPs (counted
            # once per call, an undercount of grid-many trips — the
            # overlap compute window stays conservative), and so a collective
            # hiding inside a future kernel is seen — but its internal
            # buffers are VMEM, not HBM: they contribute NOTHING to the
            # liveness peak (sub_peak stays 0).
            if not self._quiet:
                self.pallas_kernels.append(_pallas_kernel_ident(eqn))
            closed = eqn.params.get("jaxpr")
            if closed is not None:
                try:
                    self._seed_and_walk(closed, infos, env, mult, manual)
                except Exception:  # noqa: BLE001 — recognition is
                    pass           # best-effort, never aborts the audit
                # kernel buffers are VMEM: the recursive walk was for
                # recognition only, its bytes must not leak into the
                # enclosing HBM snapshot (sub_peak stays 0)
                self._sub_by = {}
            set_all([self._like_shaped_input(v, infos, avals)
                     for v in out])
        elif name == "gather":
            set_all([self._gather_prim(eqn, infos, avals, mult, src)])
        elif name in ("scatter-add", "scatter_add"):
            set_all([self._scatter_add(eqn, infos, avals, mult, src)])
        elif name == "scatter":
            set_all([self._scatter_overwrite(eqn, infos, avals, mult,
                                             src)])
        elif name in _REPLICATED_SOURCES:
            set_all([_VarInfo(_repl(len(getattr(v.aval, "shape", ()))),
                              param=True) for v in out])
        elif name == "sharding_constraint":
            set_all([self._sharding_constraint(eqn, infos[0], avals[0],
                                               mult, src)])
        elif name in ("pjit", "closed_call", "core_call", "custom_vjp_call",
                      "custom_vjp_call_jaxpr", "custom_jvp_call",
                      "remat2", "checkpoint", "custom_lin"):
            closed = (eqn.params.get("jaxpr")
                      or eqn.params.get("call_jaxpr")
                      or eqn.params.get("fun_jaxpr"))
            if closed is None:
                set_unknown()
            else:
                sub_peak, outs = self._seed_and_walk(
                    closed, infos, env, mult, manual)
                set_all(outs + [_VarInfo(None)] * (len(out) - len(outs)))
        elif name == "remat_opt":
            # custom-vjp fwd wrapper (jax >= 0.4.3x): fwd_jaxpr computes
            # primal outputs AND residuals, possibly interleaved — match
            # eqn outvars to inner outvars by shape
            closed = eqn.params.get("fwd_jaxpr")
            if closed is None:
                set_unknown()
            else:
                sub_peak, outs = self._seed_and_walk(
                    closed, infos, env, mult, manual)
                by_shape: Dict[Tuple, List[_VarInfo]] = {}
                for ov, info in zip(closed.jaxpr.outvars, outs):
                    by_shape.setdefault(
                        tuple(getattr(ov.aval, "shape", ())),
                        []).append(info)
                for v in out:
                    lst = by_shape.get(
                        tuple(getattr(v.aval, "shape", ())))
                    env[v] = lst.pop(0) if lst else _VarInfo(None)
        elif name == "scan":
            sub_peak = self._scan(eqn, infos, env, mult, manual)
        elif name == "while":
            sub_peak = self._while(eqn, infos, env, mult, manual)
        elif name == "cond":
            sub_peak = self._cond(eqn, infos, env, mult, manual, src)
        elif name == "shard_map":
            sub_peak = self._shard_map(eqn, infos, env, mult)
        elif name in _COLLECTIVES:
            self._collective(eqn, infos, avals, mult, manual, src)
            # manual collectives keep the local layout
            set_all([dataclasses.replace(i) if i.spec is not None
                     else _VarInfo(None) for i in infos[:len(out)]]
                    or [_VarInfo(None) for _ in out])
        elif name == "axis_index":
            set_all([_VarInfo(_repl(0), param=True) for _ in out])
        else:
            set_unknown()
        return sub_peak

    def _like_shaped_input(self, outvar, infos, avals) -> _VarInfo:
        shape = tuple(getattr(getattr(outvar, "aval", None), "shape", ()))
        for inf, av in zip(infos, avals):
            if (inf.spec is not None
                    and tuple(getattr(av, "shape", ())) == shape):
                return dataclasses.replace(inf)
        return _VarInfo(None, param=all(i.param for i in infos))

    def _dot_general(self, eqn, infos, avals, mult, src) -> _VarInfo:
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        li, ri = infos[0], infos[1]
        la, ra = avals[0], avals[1]
        if li.spec is None or ri.spec is None:
            return _VarInfo(None, param=li.param and ri.param)
        lspec, rspec = list(li.spec), list(ri.spec)
        # ZeRO-3 semantics, keyed on the framework's axis vocabulary
        # (parallel/mesh.py): the `fsdp` axis shards parameter STORAGE,
        # not parameter USE — a param operand entering a matmul is
        # gathered over its fsdp axes (forward and backward alike) and
        # contributes no fsdp placement to the output. Without this, a
        # transposed backward use would push the weight's fsdp axis into
        # a replicated cotangent and manufacture activation conflicts
        # downstream that the real GSPMD program never has.
        for side, aval_ in ((li, la), (ri, ra)):
            if not side.param or side.spec is None:
                continue
            zero_axes = _axes_in(side.spec) & {"fsdp"}
            if zero_axes:
                self._gather(side, aval_, zero_axes, mult, src,
                             reason="ZeRO weight gather at use")
                stripped = tuple(s - zero_axes for s in side.spec)
                if side is li:
                    lspec = list(stripped)
                else:
                    rspec = list(stripped)
        partial: FrozenSet[str] = frozenset()
        out_full = self._aval_bytes(eqn.outvars[0].aval, None)
        for ld, rd in zip(lc, rc):
            A, B = lspec[ld], rspec[rd]
            partial |= A & B
            only_a, only_b = A - B, B - A
            # one side sharded on the contracting dim, other replicated
            # there: GSPMD picks the cheaper of (a) all-gather the
            # sharded operand then matmul locally (the ZeRO weight
            # gather) and (b) slice the replicated side, matmul the
            # shard, all-reduce the output. (b) wins only when the
            # output is small relative to the operand (dgrads) — for a
            # weight feeding a huge activation, (a) does.
            if (only_a and not B) or (only_b and not A):
                oinfo, oaval, axes = ((li, la, only_a) if only_a
                                      else (ri, ra, only_b))
                gather_cost = self._aval_bytes(oaval, None)
                if gather_cost < 2 * out_full:
                    self._gather(oinfo, oaval, axes, mult, src,
                                 reason="contracting dim sharded on one "
                                        "side only")
                else:
                    partial |= axes
            elif only_a or only_b:
                # sharded on DIFFERENT axes: a real reshard. Gather the
                # param side if there is one (FSDP), else the rhs.
                loser, laval, axes = (
                    (li, la, only_a) if li.param and not ri.param
                    else (ri, ra, only_b))
                self._gather(loser, laval, axes, mult, src,
                             reason="contracting dims sharded on "
                                    "different mesh axes")
                partial |= (only_b if loser is li else only_a)
        l_free = [d for d in range(len(lspec)) if d not in lc + lb]
        r_free = [d for d in range(len(rspec)) if d not in rc + rb]
        out_spec: List[FrozenSet[str]] = []
        out_owner: List[_VarInfo] = []
        for ld, rd in zip(lb, rb):
            A, B = lspec[ld], rspec[rd]
            if A == B:
                out_spec.append(A)
                out_owner.append(li if not li.param else ri)
            elif not A or not B:
                out_spec.append(A | B)
                out_owner.append(li if A else ri)
            else:
                # batch dims sharded on different axes: same resolution
                # as elementwise — activations keep their layout
                keep, lose, laval = ((li, ri, ra) if not li.param
                                     else (ri, li, la))
                ks = A if keep is li else B
                ls = B if keep is li else A
                self._gather(lose, laval, ls - ks, mult, src,
                             reason="batch dims sharded on different "
                                    "mesh axes")
                out_spec.append(ks)
                out_owner.append(keep)
        for d in l_free:
            out_spec.append(lspec[d])
            out_owner.append(li)
        for d in r_free:
            out_spec.append(rspec[d])
            out_owner.append(ri)
        # one mesh axis claimed by two output dims — the classic FSDP
        # batch-vs-weight collision: the activation side keeps its
        # layout, the param side is gathered (that IS the planned ZeRO
        # weight gather; an activation loser is flagged by _gather)
        seen: Dict[str, int] = {}
        for d, s in enumerate(out_spec):
            for ax in sorted(s):
                if ax in partial:
                    out_spec[d] = out_spec[d] - {ax}
                    continue
                if ax not in seen:
                    seen[ax] = d
                    continue
                prev = seen[ax]
                a_own, b_own = out_owner[prev], out_owner[d]
                if a_own.param and not b_own.param:
                    lose_d, loser = prev, a_own
                else:
                    lose_d, loser = d, b_own
                self._gather(loser, la if loser is li else ra,
                             frozenset((ax,)), mult, src,
                             reason="one mesh axis cannot shard two "
                                    "output dims")
                out_spec[lose_d] = out_spec[lose_d] - {ax}
                if lose_d == prev:
                    seen[ax] = d
        self._charge_flops(eqn, avals, out_spec, partial)
        spec = self._resolve_partial(
            eqn.outvars[0].aval, out_spec, partial, mult, src,
            li.path if li.param else ri.path if ri.param else None)
        return _VarInfo(spec, param=li.param and ri.param)

    def _charge_flops(self, eqn, avals, out_spec, partial) -> None:
        """Accumulate this dot_general's per-device FLOPs into the
        innermost scan scope — the compute window the overlap model
        hides collectives behind. Per-device: the full contraction's
        2·B·M·N·K divided by the product of mesh axes sharding the
        output or reduced over (how SPMD splits the work). Counted only
        on the recording walk, once per syntactic equation — i.e. per
        scan trip."""
        if self._quiet or not self._scope_stack:
            return
        try:
            (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
            lshape = tuple(getattr(avals[0], "shape", ()))
            rshape = tuple(getattr(avals[1], "shape", ()))
            batch = math.prod(lshape[d] for d in lb) or 1
            k = math.prod(lshape[d] for d in lc) or 1
            m = math.prod(lshape[d] for d in range(len(lshape))
                          if d not in tuple(lc) + tuple(lb)) or 1
            n = math.prod(rshape[d] for d in range(len(rshape))
                          if d not in tuple(rc) + tuple(rb)) or 1
            axes = set(partial)
            for s in out_spec:
                axes |= s
            div = math.prod(self.sizes.get(ax, 1) for ax in axes) or 1
            self.scopes[self._scope_stack[-1]]["flops"] += (
                2.0 * batch * m * n * k / div)
        except Exception:  # noqa: BLE001 — accounting must not abort
            pass

    def _gather_prim(self, eqn, infos, avals, mult, src) -> _VarInfo:
        """lax.gather (embedding lookups, take_along_axis): output batch
        dims inherit the INDICES' sharding, offset dims inherit the
        operand's full-slice dims. A sharded collapsed/sliced operand dim
        (vocab-sharded embedding table, vocab-sharded logits tile) is
        modeled the way GSPMD lowers it — mask locally, psum the output
        over the lost axes — NOT as an operand all-gather."""
        operand, indices = infos[0], infos[1]
        dn = eqn.params["dimension_numbers"]
        slice_sizes = eqn.params.get("slice_sizes", ())
        out_aval = eqn.outvars[0].aval
        out_ndim = len(getattr(out_aval, "shape", ()))
        op_shape = tuple(getattr(avals[0], "shape", ()))
        if operand.spec is None or indices.spec is None:
            return _VarInfo(None, param=operand.param and indices.param,
                            path=operand.path)
        offset = set(dn.offset_dims)
        collapsed = set(dn.collapsed_slice_dims)
        out_spec: List[FrozenSet[str]] = [frozenset()] * out_ndim
        batch_out = [d for d in range(out_ndim) if d not in offset]
        for i, d in enumerate(batch_out):
            if i < len(indices.spec):
                out_spec[d] = indices.spec[i]
        lost: FrozenSet[str] = frozenset()
        op_kept = [d for d in range(len(op_shape)) if d not in collapsed]
        for od, opd in zip(sorted(offset), op_kept):
            full = (opd < len(slice_sizes)
                    and slice_sizes[opd] == op_shape[opd])
            if full:
                s = operand.spec[opd] - frozenset(
                    ax for ss in out_spec for ax in ss)
                out_spec[od] = s
            else:
                lost |= operand.spec[opd]
        for d in collapsed:
            lost |= operand.spec[d]
        lost -= frozenset(ax for s in out_spec for ax in s)
        if lost:
            payload = self._aval_bytes(out_aval, tuple(out_spec))
            self.record("psum", payload, sorted(lost), mult,
                        implicit=True, source=src,
                        param_path=operand.path)
        return _VarInfo(tuple(out_spec),
                        param=operand.param and indices.param,
                        path=operand.path or indices.path)

    def _reduce(self, eqn, infos, avals, mult, src) -> _VarInfo:
        axes_param = eqn.params.get("axes", ())
        info = infos[0]
        if info.spec is None:
            return _VarInfo(None, param=all(i.param for i in infos))
        reduced = frozenset(
            ax for d in axes_param for ax in info.spec[d])
        out_spec = [s for d, s in enumerate(info.spec)
                    if d not in set(axes_param)]
        if reduced and eqn.primitive.name in _REDUCE_COMM:
            spec = self._resolve_partial(
                eqn.outvars[0].aval, out_spec, reduced, mult, src,
                info.path)
        else:
            spec = tuple(out_spec)
        return _VarInfo(spec, param=all(i.param for i in infos),
                        path=info.path)

    def _scatter_add(self, eqn, infos, avals, mult, src) -> _VarInfo:
        # operand, indices, updates. The canonical site: an embedding
        # gradient — updates derive from dp-sharded activations, the
        # result is param-shaped and partial over those axes.
        op, _, upd = infos[0], infos[1], infos[2]
        partial = _axes_in(upd.spec) - _axes_in(op.spec)
        base = list(op.spec) if op.spec is not None else [
            frozenset() for _ in getattr(eqn.outvars[0].aval, "shape", ())]
        if partial:
            spec = self._resolve_partial(
                eqn.outvars[0].aval, base, partial, mult, src, op.path)
        else:
            spec = tuple(base)
        return _VarInfo(spec, param=op.param and upd.param, path=op.path)

    def _scatter_overwrite(self, eqn, infos, avals, mult, src) -> _VarInfo:
        # plain functional scatter (`x.at[idx].set(v)` — the serving
        # engine's per-slot paged-KV writes lower here once vmapped over
        # slots): GSPMD keeps the OPERAND's layout and reshards the
        # (small) updates to match, so the result inherits the operand
        # spec verbatim. Unlike scatter-add there is no partial sum to
        # resolve — an overwrite never manufactures a reduction.
        op, _, upd = infos[0], infos[1], infos[2]
        if op.spec is None:
            return _VarInfo(None, param=op.param and upd.param,
                            path=op.path)
        return _VarInfo(tuple(op.spec), param=op.param and upd.param,
                        path=op.path)

    def _broadcast(self, eqn, info) -> _VarInfo:
        shape = eqn.params["shape"]
        bd = eqn.params["broadcast_dimensions"]
        if info.spec is None:
            return _VarInfo(None, param=info.param, path=info.path)
        in_shape = getattr(eqn.invars[0].aval, "shape", ())
        if math.prod(shape) != int(math.prod(in_shape) or 1):
            # a TRUE broadcast (size expands): the pre-broadcast value is
            # small and cheap to re-layout, so its sharding must never
            # dominate a downstream merge (a norm scale's fsdp axis would
            # otherwise "conflict" with the activation's batch sharding
            # and invent an 8 GiB gather GSPMD never emits). Model the
            # result as replicated and let the other operand win.
            return _VarInfo(_repl(len(shape)), param=info.param,
                            path=info.path)
        out = [frozenset() for _ in shape]
        for i, od in enumerate(bd):
            if i < len(in_shape) and in_shape[i] == shape[od]:
                out[od] = info.spec[i]
        return _VarInfo(tuple(out), param=info.param, path=info.path)

    def _reshape(self, eqn, info, aval) -> _VarInfo:
        if info.spec is None:
            return _VarInfo(None, param=info.param, path=info.path)
        in_shape = tuple(getattr(aval, "shape", ()))
        out_shape = tuple(eqn.params["new_sizes"])
        try:
            spec = _reshape_spec(in_shape, info.spec, out_shape)
        except Exception:  # noqa: BLE001 — degenerate shapes: give up
            spec = None
        return _VarInfo(spec, param=info.param, path=info.path)

    def _slice(self, eqn, info, aval) -> _VarInfo:
        if info.spec is None:
            return _VarInfo(None, param=info.param, path=info.path)
        shape = getattr(aval, "shape", ())
        starts = eqn.params["start_indices"]
        limits = eqn.params["limit_indices"]
        strides = eqn.params["strides"] or (1,) * len(shape)
        new = tuple(
            s if (st == 0 and li == sz and sr == 1) else frozenset()
            for s, st, li, sr, sz in zip(
                info.spec, starts, limits, strides, shape))
        return _VarInfo(new, param=info.param, path=info.path)

    def _sharding_constraint(self, eqn, info, aval, mult,
                             src) -> _VarInfo:
        sharding = eqn.params.get("sharding")
        pspec = getattr(sharding, "spec", None)
        ndim = len(getattr(aval, "shape", ()))
        if pspec is None:
            return dataclasses.replace(info)
        annotated = self._canon(_spec_of_partition_spec(pspec, ndim))
        if info.spec is not None:
            lost = _axes_in(info.spec) - _axes_in(annotated)
            if lost:
                payload = self._aval_bytes(aval, annotated)
                self.record("all_gather", payload, sorted(lost), mult,
                            implicit=False, source=src,
                            param_path=info.path,
                            prefetchable=info.param)
        return _VarInfo(annotated, param=info.param, path=info.path)

    def _scan(self, eqn, infos, env, mult, manual) -> int:
        p = eqn.params
        closed = p["jaxpr"]
        nc, ncar = p["num_consts"], p["num_carry"]
        length = int(p.get("length", 1) or 1)
        consts, init = infos[:nc], infos[nc:nc + ncar]
        inner_mult = mult * length
        xs = []
        for inf in infos[nc + ncar:]:
            # a fresh slice arrives every trip: born at the inner mult
            xs.append(_VarInfo(
                inf.spec[1:] if inf.spec else None,
                param=inf.param, path=inf.path, born_mult=inner_mult))
        carry = [dataclasses.replace(i, born_mult=inner_mult)
                 for i in init]
        # fixpoint: a carry whose sharding changes across iterations
        # settles to the dimwise intersection (stable under repetition)
        for _ in range(2):
            self._quiet += 1
            try:
                _, outs = self._seed_and_walk(
                    closed, consts + carry + xs, env, mult, manual)
            finally:
                self._quiet -= 1
            new_carry = outs[:ncar]
            changed = False
            for i, (a, b) in enumerate(zip(carry, new_carry)):
                if a.spec != b.spec:
                    changed = True
                    if a.spec is None or b.spec is None:
                        carry[i] = _VarInfo(None, param=a.param and b.param)
                    else:
                        carry[i] = _VarInfo(
                            tuple(x & y for x, y in zip(a.spec, b.spec)),
                            param=a.param and b.param, path=a.path)
            if not changed:
                break
        sid = len(self.scopes)
        self.scopes[sid] = {"trips": length, "flops": 0.0,
                            "source": self._src(eqn), "marker": False}
        self._scope_stack.append(sid)
        try:
            sub_peak, outs = self._seed_and_walk(
                closed, consts + carry + xs, env, mult * length, manual)
        finally:
            self._scope_stack.pop()
        final = outs[:ncar]
        ys = [_VarInfo((frozenset(),) + i.spec if i.spec is not None
                       else None, param=i.param, path=i.path)
              for i in outs[ncar:]]
        for v, info in zip(eqn.outvars, final + ys):
            env[v] = info
        return sub_peak

    def _while(self, eqn, infos, env, mult, manual) -> int:
        p = eqn.params
        cn, bn = p["cond_nconsts"], p["body_nconsts"]
        body = p["body_jaxpr"]
        carry = [dataclasses.replace(i) for i in infos[cn + bn:]]
        self._quiet += 1
        try:
            _, outs = self._seed_and_walk(
                body, infos[cn:cn + bn] + carry, env, mult, manual)
        finally:
            self._quiet -= 1
        for i, (a, b) in enumerate(zip(carry, outs)):
            if a.spec != b.spec:
                carry[i] = _VarInfo(None, param=a.param and b.param)
        # trip count is dynamic: collectives inside are counted ONCE and
        # tagged unbounded (e.g. the ring-attention fori_loop)
        self._unbounded += 1
        try:
            sub_peak, outs = self._seed_and_walk(
                body, infos[cn:cn + bn] + carry, env, mult, manual)
        finally:
            self._unbounded -= 1
        for v, info in zip(eqn.outvars, outs):
            env[v] = info
        return sub_peak

    def _cond(self, eqn, infos, env, mult, manual, src) -> int:
        branches = eqn.params["branches"]
        ops = infos[1:]
        peaks, bys, outs_by_branch, sigs = [], [], [], []
        for bi, br in enumerate(branches):
            if bi > 0:
                self._quiet += 1
            try:
                pk, outs = self._seed_and_walk(br, ops, env, mult, manual)
            finally:
                if bi > 0:
                    self._quiet -= 1
            peaks.append(pk)
            bys.append(self._sub_by)
            outs_by_branch.append(outs)
            sigs.append(_collective_signature(
                getattr(br, "jaxpr", br)))
        if len({tuple(s) for s in sigs}) > 1:
            self.flag(
                "RLT303",
                "collective sequences diverge across cond branches "
                f"({[len(s) for s in sigs]} collectives per branch): "
                "ranks taking different branches issue mismatched "
                "sends/recvs and deadlock", source=src)
        merged = []
        for tup in zip(*outs_by_branch):
            m = tup[0]
            for other in tup[1:]:
                if m.spec != other.spec:
                    m = _VarInfo(None, param=m.param and other.param)
            merged.append(m)
        for v, info in zip(eqn.outvars, merged):
            env[v] = info
        if not peaks:
            return 0
        # the returned peak is the widest branch's: its per-dtype
        # breakdown must ride along or sum(peak_by) drifts off peak
        widest = max(range(len(peaks)), key=peaks.__getitem__)
        self._sub_by = bys[widest]
        return peaks[widest]

    def _shard_map(self, eqn, infos, env, mult) -> int:
        inner = eqn.params["jaxpr"]
        out_names = eqn.params.get("out_names", ())
        seeds = []
        for iv, outer in zip(inner.invars, infos):
            ndim = len(getattr(iv.aval, "shape", ()))
            seeds.append(_VarInfo(_repl(ndim), param=outer.param,
                                  path=outer.path))
        sub_env: Dict = {}
        for iv, s in zip(inner.invars, seeds):
            sub_env[iv] = s
        for cv in inner.constvars:
            sub_env[cv] = _VarInfo(
                _repl(len(getattr(cv.aval, "shape", ()))), param=True)
        sub_peak, self._sub_by = self.walk(inner, sub_env, mult, True)
        for v, names in zip(eqn.outvars, out_names):
            ndim = len(getattr(v.aval, "shape", ()))
            spec = [frozenset() for _ in range(ndim)]
            for d, axes in (names or {}).items():
                if d < ndim:
                    spec[d] = frozenset(axes)
            env[v] = _VarInfo(self._canon(tuple(spec)))
        return sub_peak

    def _collective(self, eqn, infos, avals, mult, manual, src) -> None:
        name = eqn.primitive.name
        axes = eqn.params.get("axes") or eqn.params.get("axis_name") or ()
        if not isinstance(axes, (tuple, list)):
            axes = (axes,)
        axes = tuple(a for a in axes if isinstance(a, str))
        path = next((i.path for i in infos if i.path), None)
        if name == "ppermute":
            perm = eqn.params.get("perm", ())
            group = math.prod(self.sizes.get(a, 1) for a in axes) or 1
            if not self._quiet:
                for f in check_permutation(perm, group, source=src):
                    key = ("RLT303", src, f.message[:100])
                    self._findings.setdefault(key, f)
            payload = sum(self._aval_bytes(a) for a in avals
                          if a is not None)
            self.record("ppermute", payload, axes, mult, implicit=False,
                        source=src, param_path=path,
                        dtype=_aval_dtype(avals[0] if avals else None))
            return
        if name == "all_gather":
            payload = sum(self._aval_bytes(v.aval) for v in eqn.outvars)
        else:
            payload = sum(self._aval_bytes(a) for a in avals
                          if a is not None)
        kind = {"pmax": "psum", "pmin": "psum",
                "pbroadcast": "psum"}.get(name, name)
        self.record(kind, payload, axes, mult, implicit=False,
                    source=src, param_path=path,
                    dtype=_aval_dtype(avals[0] if avals else None))


def _reshape_spec(in_shape: Tuple[int, ...],
                  in_spec: Tuple[FrozenSet[str], ...],
                  out_shape: Tuple[int, ...]) -> Tuple[FrozenSet[str], ...]:
    """Map a per-dim spec through a reshape by factor-grouping: axes
    survive when their dim maps 1:1 or is the LEADING factor of a
    collapsed/split group ([B(x), S, D] -> [B*S, D] keeps x on dim 0);
    anything subtler degrades to unsharded, never to a wrong axis."""
    out = [frozenset() for _ in out_shape]
    i = j = 0
    while i < len(in_shape) and j < len(out_shape):
        a, b = in_shape[i], out_shape[j]
        i0, j0 = i, j
        while a != b:
            if a < b:
                i += 1
                a *= in_shape[i]
            else:
                j += 1
                b *= out_shape[j]
        if i == i0 and j == j0:
            out[j] = in_spec[i]
        elif j == j0:  # collapse group: leading in-dim's axes survive
            if all(not in_spec[k] for k in range(i0 + 1, i + 1)):
                out[j] = in_spec[i0]
        else:  # split group: axes go to the leading out-dim if divisible
            out[j0] = in_spec[i0]
        i += 1
        j += 1
    return tuple(out)


def _collective_signature(jaxpr) -> List[Tuple[str, Tuple]]:
    """(prim, axes) sequence of every collective in program order,
    recursively — the cond-branch divergence comparator."""
    sig: List[Tuple[str, Tuple]] = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in _COLLECTIVES:
            axes = (eqn.params.get("axes")
                    or eqn.params.get("axis_name") or ())
            if not isinstance(axes, (tuple, list)):
                axes = (axes,)
            sig.append((eqn.primitive.name, tuple(map(str, axes))))
        for v in eqn.params.values():
            for x in (v if isinstance(v, (tuple, list)) else (v,)):
                inner = getattr(x, "jaxpr", x)
                if hasattr(inner, "eqns"):
                    sig.extend(_collective_signature(inner))
    return sig


# --------------------------------------------------------------------------
# overlap classification (hidden vs exposed collective time)
# --------------------------------------------------------------------------


def classify_overlap(
    events: Sequence[CollectiveEvent],
    scopes: Mapping[int, Mapping[str, Any]],
    topo: Topology,
    scheduled: Optional[bool] = None,
) -> Dict[str, Any]:
    """Classify each collective as hidden-behind-compute vs exposed and
    annotate ``events`` in place (``hidden_us``).

    The model (docs/STATIC_ANALYSIS.md "overlap model"):

      * only PREFETCHABLE collectives inside a scanned body are
        hideable — ZeRO weight gathers (operands known ahead of use)
        and param-matched grad reduce-scatters (retired per trip by the
        backward scan);
      * a scope's per-trip compute window is its counted dot_general
        FLOPs at the derated roofline (`costmodel.compute_time_us`);
        pallas kernels and elementwise work are not counted, so the
        window — and with it the hidden share — is conservative;
      * ``scheduled`` is the program-wide flag (the double-buffer
        fingerprint `ops.dispatch.OVERLAP_PREFETCH_NAME` anywhere in
        the trace; when None it defaults to "any scope carries the
        marker") — but hidden credit is PER SCOPE: a scope earns it
        only when its source location is one a marker was seen in.
        The backward scan is the transpose of the marked forward and
        shares its source (marker-free by construction, still
        credited); an unrelated scan in the same program — e.g. the
        fused-CE chunk loop — is NOT part of the schedule and hides
        nothing, no matter how large its compute window;
      * per scope: hidden fraction = min(1, window / per-trip
        prefetchable comm); each event hides that fraction of its time.
        A zero-compute scope (the pathological case: nothing to hide
        behind) hides nothing.

    Returns the overlap summary dict carried by `TraceReport.overlap`.
    """
    if scheduled is None:
        scheduled = any(s.get("marker") for s in scopes.values())
    marked_sources = {str(s.get("source", f"scan#{sid}"))
                      for sid, s in scopes.items() if s.get("marker")}
    for e in events:
        e.hidden_us = 0.0
    per_scope: List[Dict[str, Any]] = []
    for sid in sorted(scopes):
        sc = scopes[sid]
        evs = [e for e in events if e.scope == sid and e.prefetchable]
        if not evs:
            continue
        source = str(sc.get("source", f"scan#{sid}"))
        in_schedule = source in marked_sources
        trips = max(1, int(sc.get("trips", 1)))
        comm = sum(e.time_us for e in evs)
        comm_trip = comm / trips
        window = compute_time_us(float(sc.get("flops", 0.0)), topo)
        frac = 0.0
        if scheduled and in_schedule and comm_trip > 0:
            frac = min(1.0, window / comm_trip)
        for e in evs:
            e.hidden_us = e.time_us * frac
        per_scope.append({
            "source": source,
            "trips": trips,
            "scheduled": in_schedule,
            "compute_us_per_trip": round(window, 1),
            "prefetch_comm_us_per_trip": round(comm_trip, 1),
            "hidden_fraction": round(frac, 4),
        })
    pref = sum(e.time_us for e in events if e.prefetchable)
    hidden = sum(e.hidden_us for e in events)
    total = sum(e.time_us for e in events)
    return {
        "scheduled": bool(scheduled),
        "overlap_hidden_fraction": round(hidden / pref, 4) if pref else 0.0,
        "ici_hidden_us": round(hidden, 1),
        "ici_exposed_us": round(total - hidden, 1),
        "prefetchable_time_us": round(pref, 1),
        "per_scope": per_scope,
    }


# --------------------------------------------------------------------------
# building + auditing the canonical step
# --------------------------------------------------------------------------


def trace_step(module, strategy, n_devices: int, example_batch: Any):
    """Trace the canonical donated train step (the Trainer's loss ->
    grads -> tx.update -> apply_updates shape) over abstractions and
    return ``(closed_jaxpr, meta)``. Zero devices: the same
    AbstractMesh + eval_shape build as `check_plan`/`plan_train_memory`
    (the strategy instance is consumed — pass a fresh one)."""
    import jax

    from ray_lightning_tpu.ops.dispatch import force_pallas
    from ray_lightning_tpu.parallel.plan import _abstract, abstract_mesh
    from ray_lightning_tpu.utils.pytree import named_leaves

    spec = strategy.build_spec(n_devices).resolve(n_devices)
    mesh = abstract_mesh(spec)
    strategy.spec = spec
    strategy.mesh = mesh
    strategy.bind_module(module)
    module.setup()

    a_key = jax.eval_shape(lambda: jax.random.key(0))
    a_batch = _abstract(example_batch)
    # force_pallas, not force_xla: the audit must see the program the
    # TPU runs (flash kernel — no [S, S] score buffer), and like
    # force_xla it skips the backend probe so no device is initialized
    with force_pallas():
        a_params = jax.eval_shape(module.init_params, a_key, a_batch)
        p_shardings = strategy.param_shardings(a_params)
        tx = module.configure_optimizers()
        a_opt = jax.eval_shape(tx.init, a_params)
        o_shardings = strategy.opt_state_shardings(a_opt, a_params)

        def loss_fn(params, batch, rng):
            out = module.training_step(params, batch, rng)
            loss = out[0] if isinstance(out, tuple) else out
            metrics = out[1] if isinstance(out, tuple) else {}
            return loss, {**metrics, **module.pop_logged()}

        def step(params, opt_state, batch, rng):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch, rng)
            updates, opt_state = tx.update(grads, opt_state, params)
            import optax

            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, metrics

        closed = jax.make_jaxpr(step)(a_params, a_opt, a_batch, a_key)
    closed = _dce(closed)

    meta = {
        "spec": spec,
        "mesh_sizes": spec.sizes(),
        "a_params": a_params,
        "a_opt": a_opt,
        "a_batch": a_batch,
        "p_shardings": p_shardings,
        "o_shardings": o_shardings,
        "named_params": dict(named_leaves(a_params)),
        "named_opt": dict(named_leaves(a_opt)),
        "batch_pspec": strategy.batch_spec(),
    }
    return closed, meta


def _dce(closed):
    """Dead-code-eliminate the traced jaxpr (all outputs kept, all
    invars kept) so the audit walks the program XLA actually compiles.
    jit runs the same pass before lowering; without it the walk charges
    vestigial residuals AD plumbing leaves behind — e.g. grad-of-scan
    under the overlap schedule stacks the gathered weight carry as ys
    that NOTHING in the backward scan consumes (measured: a phantom
    full-stack copy, ~26 GiB on llama3-8b). Degrades to the raw jaxpr
    if the DCE helper is unavailable."""
    try:
        import jax
        from jax.interpreters import partial_eval as _pe

        jaxpr, _ = _pe.dce_jaxpr(
            closed.jaxpr, [True] * len(closed.jaxpr.outvars),
            instantiate=True)
        return jax.core.ClosedJaxpr(jaxpr, closed.consts)
    except Exception:  # noqa: BLE001 — an uncooperative jax version
        # costs precision, never the audit
        return closed


def audit_step(
    module,
    strategy,
    example_batch: Any,
    *,
    topology="v5p-8",
    n_devices: Optional[int] = None,
    reserve_fraction: float = 0.10,
    label: str = "",
    numerics: bool = True,
) -> TraceReport:
    """Full tracecheck audit: trace the real jitted step for ``module``
    under ``strategy`` on ``topology`` (a name like "v5p-64" or a
    `costmodel.Topology`) and return the `TraceReport` — collective
    schedule, implicit-reshard findings, ring checks, and the peak-HBM
    estimate vs the chip budget. CPU-only; consumes ``strategy``.

    ``numerics`` runs numcheck's dtype-provenance pass over the same
    jaxpr (RLT801-805) and fills `TraceReport.precision` — the
    per-dtype-class byte ledger plus the loss's widest-path dtype;
    ``numerics=False`` (the CLI's ``--no-numerics``) skips both."""
    import jax

    topo = (topology if isinstance(topology, Topology)
            else parse_topology(topology))
    if n_devices is None:
        n_devices = topo.n_devices
    closed, meta = trace_step(module, strategy, n_devices, example_batch)
    sizes = meta["mesh_sizes"]
    live_axes = {ax for ax, s in sizes.items() if s > 1}

    def canon(spec):
        return tuple(frozenset(ax for ax in s if ax in live_axes)
                     for s in spec)

    # the ZeRO reduce_scatter matcher: param/opt shapes (and their
    # scan-stacked suffixes) with their composed specs
    param_shapes: Dict[Tuple, Tuple[Spec, str]] = {}

    def feed(named, shardings, prefix):
        for (path, leaf), sh in zip(
                named.items(), jax.tree.leaves(shardings)):
            shape = tuple(getattr(leaf, "shape", ()))
            spec = canon(_spec_of_partition_spec(
                getattr(sh, "spec", sh), len(shape)))
            param_shapes.setdefault(shape, (spec, f"{prefix}/{path}"))
            if len(shape) >= 2:
                param_shapes.setdefault(
                    shape[1:], (spec[1:], f"{prefix}/{path}"))

    feed(meta["named_params"], meta["p_shardings"], "params")
    feed(meta["named_opt"], meta["o_shardings"], "opt_state")

    auditor = _StepAuditor(sizes, topo, param_shapes)

    # seed the top-level env: flatten order mirrors the step signature
    env: Dict = {}
    seeds: List[_VarInfo] = []
    for (path, leaf), sh in zip(meta["named_params"].items(),
                                jax.tree.leaves(meta["p_shardings"])):
        ndim = len(getattr(leaf, "shape", ()))
        seeds.append(_VarInfo(
            canon(_spec_of_partition_spec(getattr(sh, "spec", sh), ndim)),
            param=True, path=f"params/{path}"))
    for (path, leaf), sh in zip(meta["named_opt"].items(),
                                jax.tree.leaves(meta["o_shardings"])):
        ndim = len(getattr(leaf, "shape", ()))
        seeds.append(_VarInfo(
            canon(_spec_of_partition_spec(getattr(sh, "spec", sh), ndim)),
            param=True, path=f"opt_state/{path}"))
    from ray_lightning_tpu.utils.pytree import named_leaves

    batch_pspec = meta["batch_pspec"]
    for path, leaf in named_leaves(meta["a_batch"]):
        ndim = len(getattr(leaf, "shape", ()))
        seeds.append(_VarInfo(
            canon(_spec_of_partition_spec(batch_pspec, ndim)),
            param=False, path=f"batch/{path}"))
    seeds.append(_VarInfo(None, param=True, path="rng"))  # key leaf

    jaxpr = closed.jaxpr
    n = min(len(jaxpr.invars), len(seeds))
    for v, s in zip(jaxpr.invars[:n], seeds[:n]):
        env[v] = s
    for v in jaxpr.invars[n:]:
        env[v] = _VarInfo(None)
    for v in jaxpr.constvars:  # hoisted trace-time constants: replicated
        env[v] = _VarInfo(_repl(len(getattr(v.aval, "shape", ()))),
                          param=True)

    peak, peak_by = auditor.walk(jaxpr, env, 1, False)

    def _by_dtype(named, seed_slice) -> Dict[str, int]:
        # per-dtype itemization of the SAME per-leaf bytes the scalar
        # totals sum — the ledger identity sum(by.values()) == total
        # holds exactly (test-pinned)
        by: Dict[str, int] = {}
        for (_, leaf), s in zip(named.items(), seed_slice):
            b = auditor._aval_bytes(leaf, s.spec)
            if b:
                dt = str(getattr(leaf, "dtype", "opaque"))
                by[dt] = by.get(dt, 0) + b
        return by

    params_by = _by_dtype(meta["named_params"], seeds)
    params_dev = sum(params_by.values())
    np_ = len(meta["named_params"])
    opt_by = _by_dtype(meta["named_opt"], seeds[np_:])
    opt_dev = sum(opt_by.values())

    events = auditor.events
    overlap = classify_overlap(events, auditor.scopes, topo,
                               scheduled=auditor.saw_prefetch_marker)

    findings = auditor.findings
    if topo.n_slices > 1 and n_devices == topo.n_devices:
        # multi-slice placement audit (docs/ELASTIC.md "DCN cost
        # model"): with the slice-major layout the mesh layer builds
        # (order_devices_for_slices), only the outermost `data` axis
        # may span slices — its cross-slice traffic is the hierarchical
        # gradient reduction, priced above. Any OTHER axis crossing the
        # boundary puts per-layer collectives on DCN: flag it. A mesh
        # SMALLER than the deployment (n_devices override) packs into
        # the fewest slices and is never flagged (same guard as
        # _dcn_span).
        from ray_lightning_tpu.parallel.plan import dcn_crossing_axes

        for ax, span in sorted(
                dcn_crossing_axes(sizes, topo.n_slices).items()):
            if ax == "data":
                continue
            findings.append(Finding(
                "RLT306",
                f"mesh axis '{ax}' (size {sizes.get(ax)}) spans {span} "
                f"DCN slices on {topo.name}: its collectives ride the "
                f"inter-slice network ({topo.dcn_gbps:.1f} GB/s per "
                f"chip vs {topo.ici_gbps:.0f} GB/s ICI) every step — "
                "place only `data` across slices and keep "
                f"'{ax}' within a slice "
                f"(<= {topo.devices_per_slice} devices)",
                symbol=label or topo.name))
    if not auditor.saw_prefetch_marker:
        # RLT305 exposed-collective-in-scan: a per-trip ZeRO weight
        # gather inside a scanned body with no prefetch schedule.
        # Hoisted loop-invariant gathers are excluded by comparing the
        # charged count against the scope's trip count: a hoisted
        # gather is charged once per walk (fwd+bwd -> count 2), a
        # per-trip one at least once per trip (e.g. the lm_head gather
        # in the 512-trip CE chunk scan is hoisted — count 2 << 512 —
        # and the overlap knob could not hide it anyway).
        seen_305 = set()
        for e in events:
            scope_trips = int(
                auditor.scopes.get(e.scope, {}).get("trips", 1))
            if (e.prefetchable and e.kind == "all_gather"
                    and e.scope is not None and not e.unbounded
                    and scope_trips > 1 and e.count >= scope_trips):
                key = (e.source, e.param_path)
                if key in seen_305:
                    continue
                seen_305.add(key)
                findings.append(Finding(
                    "RLT305",
                    f"blocking weight all-gather "
                    f"({_fmt_bytes(e.wire_bytes).strip()} over "
                    f"{'x'.join(e.axes)}, x{e.count} trips) sits "
                    "exposed inside a scanned layer body; its operand "
                    "is a parameter slice known one trip ahead — "
                    "enable the sharding plan's overlap knob "
                    "(FSDP/ShardedMesh(overlap='on')) to hide it "
                    f"behind the previous layer's compute [at "
                    f"{e.source}]",
                    symbol=e.param_path or e.source))
    precision: Optional[Dict[str, Any]] = None
    if numerics:
        from ray_lightning_tpu.analysis import numcheck as _numcheck

        # outvar layout of the canonical step: new-param leaves, then
        # new-opt leaves, then the scalar loss, then metrics — the loss
        # output sits right past the state
        loss_index = np_ + len(meta["named_opt"])
        nc_findings, nc_info = _numcheck.numcheck_jaxpr(
            closed, loss_index=loss_index)
        findings.extend(nc_findings)
        findings.extend(_numcheck.check_gradient_collectives(
            events, meta["named_params"], meta["named_opt"]))
        # activations = what the liveness peak holds per dtype beyond
        # the resident params/opt state (clamped: state leaves already
        # freed at the peak instant don't go negative)
        act_by: Dict[str, int] = {}
        for dt, b in peak_by.items():
            rem = b - params_by.get(dt, 0) - opt_by.get(dt, 0)
            if rem > 0:
                act_by[dt] = rem
        precision = {
            "params": params_by,
            "opt_state": opt_by,
            "activations": act_by,
            "kv_pool": {},
            "loss_widest_dtype": nc_info.get("loss_widest_dtype"),
        }

    budget = int(topo.hbm_bytes * (1 - reserve_fraction))
    if peak > budget:
        gib = 1024**3
        findings.append(Finding(
            "RLT302",
            f"estimated peak HBM {peak / gib:.2f} GiB/device exceeds the "
            f"{topo.device_kind} budget {budget / gib:.2f} GiB "
            f"({topo.hbm_gib:.0f} GiB x {1 - reserve_fraction:.0%} "
            "usable): the step will OOM on this topology",
            symbol=label or topo.name))
    return TraceReport(
        topology=topo,
        mesh_axes={k: v for k, v in sizes.items() if v > 1},
        collectives=events,
        overlap=overlap,
        findings=findings,
        params_bytes_per_device=params_dev,
        opt_bytes_per_device=opt_dev,
        peak_hbm_bytes=peak,
        hbm_budget_bytes=budget,
        label=label,
        precision=precision,
    )
