"""Per-topology interconnect/HBM cost model for tracecheck.

tracecheck (analysis/tracecheck.py) turns a jitted train step into a
collective schedule; this module turns that schedule into bytes-on-wire
and a latency estimate for a NAMED topology ("v5p-64") — zero hardware,
so the numbers are a *model*, not a measurement. The HBM side reuses the
planner's hardware table (`parallel.plan.hbm_bytes_for_kind`), keeping
one source of truth for per-chip memory; the ICI side adds the
bandwidth/latency figures the planner never needed.

Model assumptions (documented in docs/STATIC_ANALYSIS.md):

  * bandwidth figures are the PUBLISHED aggregate ICI bytes/s per chip
    (all links combined). Ring algorithms use every link of the group's
    torus dimension, so charging the aggregate is the optimistic bound;
    contention with other collectives is not modeled;
  * collective wire cost per chip follows the standard ring algebra over
    group size n: all_gather / reduce_scatter move (n-1)/n of the full
    payload, an all_reduce (psum) is reduce_scatter + all_gather =
    2(n-1)/n, a ppermute moves exactly its payload one hop, all_to_all
    moves (n-1)/n;
  * latency = hops x per-hop ICI latency + wire_bytes / bandwidth, with
    hops = n-1 for ring collectives and 1 for a neighbor permute;
  * DCN (multi-slice): ``parse_topology("2xv5p-64")`` is TWO v5p-64
    slices joined over the data-center network — 128 chips, two network
    tiers. A collective whose group spans slices is priced
    HIERARCHICALLY (the standard two-level ring): the intra-slice stage
    over n/s members rides ICI, the inter-slice stage over s slices
    rides DCN on the already-reduced/sharded payload (payload/n_intra
    per chip). DCN bandwidth/latency figures are per-chip share of the
    published inter-slice fabric — an order of magnitude below ICI,
    which is exactly why the mesh layer places only the `data` axis
    across slices (parallel/mesh.py order_devices_for_slices) and
    tracecheck flags any OTHER axis crossing the boundary (RLT306);
  * the overlap model (`compute_time_us`, consumed by tracecheck's
    hidden-vs-exposed classification): a scanned body's per-trip compute
    window is its counted matmul FLOPs (dot_general only — pallas
    kernels and elementwise work are NOT counted, an undercount that
    makes the hidden fraction conservative) over the chip's spec-sheet
    peak derated by MXU_EFFICIENCY. A prefetch-scheduled collective is
    hidden up to that window; what does not fit stays exposed.
"""
from __future__ import annotations

import dataclasses
import math
import re
from typing import Dict, Mapping, Optional, Tuple

from ray_lightning_tpu.parallel.plan import hbm_bytes_for_kind

__all__ = [
    "Topology", "CollectiveCost", "ICI_SPECS", "DCN_SPECS",
    "MXU_EFFICIENCY", "DTYPE_WIDTHS", "dtype_width",
    "parse_topology", "topology_for_kind",
    "collective_cost", "compute_time_us",
    "paged_decode_traffic_bytes", "paged_prefill_traffic_bytes",
]

#: canonical storage width in BYTES per dtype name — the ONE table both
#: plan_checker's RLT105 (opt state wider than its param) and numcheck's
#: RLT804 (gradient collective narrower than its opt state) read, so the
#: two rules cannot drift (tests/test_numcheck.py pins this). Names are
#: the `str(np.dtype)` / jax aval spellings the analyzers see; the jax
#: sub-byte int4/uint4 and the fp8 family are listed explicitly because
#: np.dtype() cannot resolve them everywhere.
DTYPE_WIDTHS: Dict[str, float] = {
    "float64": 8.0, "int64": 8.0, "uint64": 8.0, "complex64": 8.0,
    "float32": 4.0, "int32": 4.0, "uint32": 4.0,
    "bfloat16": 2.0, "float16": 2.0, "int16": 2.0, "uint16": 2.0,
    "float8_e4m3fn": 1.0, "float8_e5m2": 1.0, "float8_e4m3b11fnuz": 1.0,
    "int8": 1.0, "uint8": 1.0, "bool": 1.0,
    "int4": 0.5, "uint4": 0.5,
}


def dtype_width(dtype) -> Optional[float]:
    """Storage width in bytes for a dtype (object or name); None when
    unknown. Falls back to numpy's itemsize for names not in the table
    (exotic structured dtypes) so callers degrade to the historical
    `.itemsize` behavior instead of silently skipping the check."""
    name = getattr(dtype, "name", None) or str(dtype)
    w = DTYPE_WIDTHS.get(name)
    if w is not None:
        return w
    try:
        import numpy as np

        return float(np.dtype(name).itemsize)
    except Exception:
        return None

#: ICI spec sheet per device family: (device_kind for the HBM table,
#: aggregate ICI GB/s per chip, per-hop latency in microseconds).
#: Bandwidths are the public per-chip interconnect figures (v4 2400
#: Gbps, v5e 1600, v5p 4800, v6e 3584); "cpu" is the CI pseudo-family
#: (loopback, spec-sheet-free) so tests and laptops can run the same
#: code path with an explicit hbm override.
ICI_SPECS: Dict[str, Tuple[str, float, float]] = {
    "v3": ("TPU v3", 280.0, 1.5),
    "v4": ("TPU v4", 300.0, 1.0),
    "v5e": ("TPU v5e", 200.0, 1.0),
    "v5litepod": ("TPU v5 lite", 200.0, 1.0),
    "v5p": ("TPU v5p", 600.0, 1.0),
    "v6e": ("TPU v6e", 448.0, 1.0),
    "cpu": ("cpu", 10.0, 10.0),
}

#: device_kind -> family, for topology_for_kind (the reverse lookup of
#: ICI_SPECS' first column)
_KIND_TO_FAMILY = {kind: fam for fam, (kind, _, _) in ICI_SPECS.items()}

#: DCN (inter-slice) figures per family: (GB/s per chip, per-hop latency
#: in microseconds). These model each chip's SHARE of the slice's
#: data-center-network uplink under a hierarchical collective (every
#: chip drives its own inter-slice ring on its reduce-scattered shard) —
#: deliberately coarse, an order of magnitude below ICI, because the
#: number that matters is the TIER RATIO: it is what makes a tensor/fsdp
#: axis across DCN a performance cliff and a data axis across DCN a
#: tolerable gradient-reduction tax ("Exploring the limits of
#: Concurrency in ML Training on Google TPUs"; TorchTitan HSDP).
#: "cpu" keeps CI runnable with visible-but-tiny figures.
DCN_SPECS: Dict[str, Tuple[float, float]] = {
    "v3": (6.25, 50.0),
    "v4": (12.5, 50.0),
    "v5e": (6.25, 50.0),
    "v5litepod": (6.25, 50.0),
    "v5p": (25.0, 50.0),
    "v6e": (12.5, 50.0),
    "cpu": (1.0, 100.0),
}

#: fallback HBM for families the planner table doesn't know (the "cpu"
#: pseudo-family): enough to trace, small enough that a real model's
#: HBM-OVERCOMMIT check still exercises on CI
_CPU_HBM_BYTES = 16 * 1024**3


@dataclasses.dataclass(frozen=True)
class Topology:
    """One named deployment: chip kind + count + interconnect figures.
    ``n_slices > 1`` is a multi-slice deployment (``"2xv5p-64"``):
    ``n_devices`` is the TOTAL chip count across slices, ICI spans one
    slice, slices talk over DCN at the dcn_* figures."""

    name: str             # e.g. "v5p-64" or "2xv5p-64"
    device_kind: str      # PJRT device_kind string, keys the HBM table
    n_devices: int
    ici_gbps: float       # aggregate ICI bandwidth per chip, GB/s
    ici_hop_latency_us: float
    hbm_bytes: int        # usable HBM per chip
    #: spec-sheet peak bf16 TFLOP/s per chip — the compute side of the
    #: overlap model's roofline. None resolves from device_kind via the
    #: utils/probe.py table (one source of truth), so a directly
    #: constructed Topology prices compute the same as parse_topology.
    peak_tflops: Optional[float] = None
    #: multi-slice (DCN) tier. Defaults keep every existing
    #: single-slice construction site valid: one slice, DCN figures
    #: resolved from the device kind's family in __post_init__.
    n_slices: int = 1
    dcn_gbps: Optional[float] = None
    dcn_hop_latency_us: Optional[float] = None

    def __post_init__(self) -> None:
        if self.peak_tflops is None:
            object.__setattr__(
                self, "peak_tflops", _peak_tflops(self.device_kind))
        if self.dcn_gbps is None or self.dcn_hop_latency_us is None:
            fam = _KIND_TO_FAMILY.get(self.device_kind, "cpu")
            gbps, lat = DCN_SPECS.get(fam, DCN_SPECS["cpu"])
            if self.dcn_gbps is None:
                object.__setattr__(self, "dcn_gbps", gbps)
            if self.dcn_hop_latency_us is None:
                object.__setattr__(self, "dcn_hop_latency_us", lat)
        if self.n_slices < 1 or self.n_devices % self.n_slices:
            raise ValueError(
                f"topology {self.name!r}: {self.n_devices} devices do "
                f"not split into {self.n_slices} equal slices")

    @property
    def hbm_gib(self) -> float:
        return self.hbm_bytes / 1024**3

    @property
    def devices_per_slice(self) -> int:
        return self.n_devices // self.n_slices

    def describe(self) -> str:
        base = (f"{self.name}: {self.n_devices}x {self.device_kind} "
                f"({self.hbm_gib:.0f} GiB HBM, {self.ici_gbps:.0f} GB/s "
                "ICI per chip)")
        if self.n_slices > 1:
            base += (f" in {self.n_slices} slices of "
                     f"{self.devices_per_slice} over DCN "
                     f"({self.dcn_gbps:.1f} GB/s per chip)")
        return base


def parse_topology(name: str, *,
                   hbm_bytes: Optional[int] = None) -> Topology:
    """``"v5p-64"`` -> a Topology; ``"2xv5p-64"`` -> TWO v5p-64 slices
    joined over DCN (128 chips total, ``n_slices=2``). The family keys
    ICI_SPECS; the chip count after the dash is PER SLICE. Unknown
    families raise listing the known ones (same first-contact contract
    as hbm_bytes_for_kind)."""
    m = re.fullmatch(r"(?:(\d+)x)?([a-z][a-z0-9]*?)-(\d+)",
                     name.strip().lower())
    if not m:
        raise ValueError(
            f"cannot parse topology {name!r}; expected <family>-<chips> "
            "like 'v5p-64', or <slices>x<family>-<chips> like "
            f"'2xv5p-64' (families: {sorted(ICI_SPECS)})")
    slices = int(m.group(1) or 1)
    family, count = m.group(2), int(m.group(3))
    if family not in ICI_SPECS:
        raise ValueError(
            f"unknown topology family {family!r} (known: "
            f"{sorted(ICI_SPECS)}); pass hbm_bytes= and use "
            "topology_for_kind for other hardware")
    if count < 1:
        raise ValueError(f"topology {name!r} must have >= 1 chip")
    if slices < 1:
        raise ValueError(f"topology {name!r} must have >= 1 slice")
    kind, gbps, lat = ICI_SPECS[family]
    if hbm_bytes is None:
        try:
            hbm_bytes = hbm_bytes_for_kind(kind)
        except ValueError:  # the "cpu" pseudo-family
            hbm_bytes = _CPU_HBM_BYTES
    return Topology(name=name, device_kind=kind, n_devices=slices * count,
                    ici_gbps=gbps, ici_hop_latency_us=lat,
                    hbm_bytes=int(hbm_bytes), n_slices=slices)


def topology_for_kind(device_kind: str, n_devices: int, *,
                      hbm_bytes: Optional[int] = None) -> Topology:
    """Topology from a PJRT ``device_kind`` string (the plan CLI's
    --device-kind vocabulary) instead of a family-dash-count name.
    Unknown kinds get the cpu pseudo-family's conservative ICI figures —
    the HBM side still honors ``hbm_bytes`` or the planner table."""
    family = _KIND_TO_FAMILY.get(device_kind, "cpu")
    _, gbps, lat = ICI_SPECS[family]
    if hbm_bytes is None:
        try:
            hbm_bytes = hbm_bytes_for_kind(device_kind)
        except ValueError:
            hbm_bytes = _CPU_HBM_BYTES
    return Topology(name=f"{family}-{n_devices}", device_kind=device_kind,
                    n_devices=n_devices, ici_gbps=gbps,
                    ici_hop_latency_us=lat, hbm_bytes=int(hbm_bytes))


def _peak_tflops(device_kind: str) -> float:
    """Spec-sheet peak for the overlap roofline — one source of truth
    with the bench/doctor probe (utils/probe.py); unknown kinds get the
    v5e-class fallback, same contract as the probe."""
    from ray_lightning_tpu.utils.probe import device_peak_tflops

    return float(device_peak_tflops(device_kind))


#: fraction of spec-sheet peak a well-tuned matmul-dominated step
#: actually sustains — the compute window for hiding collectives is
#: charged at peak x efficiency. 0.6 is the repo's own measured MFU
#: band at the flagship shapes (BENCH_r03: 0.59 best); a HIGHER
#: efficiency would shrink the window and under-claim hiding, a lower
#: one would over-claim. Documented in docs/STATIC_ANALYSIS.md.
MXU_EFFICIENCY = 0.6


def compute_time_us(flops: float, topo: Topology) -> float:
    """Time to execute ``flops`` per-device FLOPs on one chip of
    ``topo`` at the derated roofline — the overlap model's per-trip
    compute window."""
    if flops <= 0:
        return 0.0
    return flops / (topo.peak_tflops * 1e12 * MXU_EFFICIENCY) * 1e6


@dataclasses.dataclass(frozen=True)
class CollectiveCost:
    wire_bytes: int   # bytes each chip puts on ICI for this collective
    time_us: float    # ring-model latency estimate (both tiers, serial)
    #: bytes each chip puts on DCN (0 on a single-slice group). When
    #: nonzero, ``time_us`` already includes the DCN stage — the two
    #: tiers are priced as sequential hierarchical stages.
    dcn_bytes: int = 0
    dcn_time_us: float = 0.0


def _ring(kind: str, payload: float, n: int) -> Tuple[float, int]:
    """(wire bytes per chip, ring hops) for one single-tier collective
    over group size ``n`` — the standard ring algebra."""
    if n <= 1:
        return 0.0, 0
    frac = (n - 1) / n
    if kind in ("all_gather", "reduce_scatter", "all_to_all"):
        return payload * frac, n - 1
    if kind == "ppermute":
        return float(payload), 1
    # psum / pmax / pmin / pbroadcast and friends: all_reduce-shaped
    return 2.0 * payload * frac, 2 * (n - 1)


def collective_cost(
    kind: str,
    payload_bytes: int,
    axis_sizes: Mapping[str, int],
    topo: Topology,
    *,
    dcn_group: int = 1,
) -> CollectiveCost:
    """Ring-model wire bytes + latency for ONE collective.

    ``payload_bytes`` is the per-chip payload the jaxpr shows: the local
    operand bytes for psum/ppermute/all_to_all/reduce_scatter, and the
    per-chip FULL (post-gather) bytes for all_gather. ``axis_sizes`` maps
    the participating mesh axes to their sizes; the group size is their
    product.

    ``dcn_group`` is the number of DCN slices the group spans (1 =
    intra-slice; use `parallel.plan.group_dcn_span` to derive it from
    the mesh layout). A crossing group is priced as the hierarchical
    two-level algorithm: the intra-slice stage over n/dcn_group members
    rides ICI; the inter-slice stage rides DCN on the intra-reduced (or
    intra-sharded) payload — each chip drives its own inter-slice ring
    on a 1/n_intra share, the standard two-level all-reduce. Two
    exceptions with NO intra-stage payload reduction: a crossing
    ppermute puts its whole payload on DCN (one hop), and a crossing
    all_to_all sends its chunks directly — the (s-1)/s fraction
    targeting remote slices crosses DCN at full size."""
    n = max(1, math.prod(axis_sizes.values()))
    if n == 1:
        return CollectiveCost(0, 0.0)
    s = max(1, min(int(dcn_group), n))
    if n % s:
        # a group that touches s slices unevenly degrades to the
        # conservative read: price the whole group on DCN figures
        s = n
    n_intra = n // s
    if kind == "ppermute" and s > 1:
        dcn_wire, dcn_hops = float(payload_bytes), 1
        ici_wire, ici_hops = 0.0, 0
    elif kind == "all_to_all" and s > 1:
        # all_to_all has NO intra-stage payload reduction (unlike the
        # reduce/gather shapes below): each chip's payload splits into
        # n equal chunks sent directly — n_intra-1 stay on ICI, the
        # (s-1)/s fraction targeting remote slices crosses DCN whole
        ici_wire = payload_bytes * (n_intra - 1) / n
        ici_hops = max(0, n_intra - 1)
        dcn_wire = payload_bytes * (s - 1) / s
        dcn_hops = s - 1
    else:
        ici_wire, ici_hops = _ring(kind, payload_bytes, n_intra)
        dcn_wire, dcn_hops = _ring(kind, payload_bytes / n_intra, s)
    ici_time = (ici_wire / (topo.ici_gbps * 1e3)
                + ici_hops * topo.ici_hop_latency_us)
    dcn_time = 0.0
    if s > 1:
        dcn_time = (dcn_wire / (topo.dcn_gbps * 1e3)
                    + dcn_hops * topo.dcn_hop_latency_us)
    else:
        dcn_wire = 0.0
    return CollectiveCost(int(ici_wire), ici_time + dcn_time,
                          dcn_bytes=int(dcn_wire),
                          dcn_time_us=dcn_time)


def paged_decode_traffic_bytes(pool_bytes: int, gathered_view_bytes: int,
                               fused: bool) -> int:
    """Per-tick HBM *traffic* of the serving decode lane's KV movement
    (docs/SERVING.md "paged-attention kernel") — the bandwidth story
    behind the capacity numbers `serve_kv_plan_bytes` itemizes.

    Decode is bandwidth-bound: every tick must stream each live slot's
    K/V once (<= the pool, read). The reference lane additionally
    WRITES the dense gathered view and READS it back through the
    model's cache path — the copy is the traffic, not just the HBM.
    The fused kernel streams the table-named blocks straight through
    VMEM, so its traffic floor is the single pool read. A conservative
    per-tick model (the full pool charged even when slots are idle;
    Q/output/weight bytes excluded — identical on both paths)."""
    if fused:
        return int(pool_bytes)
    return int(pool_bytes + 2 * gathered_view_bytes)


def paged_prefill_traffic_bytes(group_view_bytes: int, chunk_bytes: int,
                                fused: bool) -> int:
    """Per-chunk HBM *traffic* of the serving PREFILL lane's KV
    movement (docs/SERVING.md "paged prefill kernel") — the prefill
    twin of `paged_decode_traffic_bytes`.

    Every chunk must stream the group's already-written blocks once
    (<= the group's span, read) and write the chunk's new K/V. The
    reference lane additionally WRITES the dense per-group gathered
    view and READS it back through the model's chunked cache path —
    the copy is the traffic. The fused kernel streams the table-named
    blocks straight through VMEM, so its traffic floor is the group's
    block reads plus the chunk write. A conservative per-chunk model
    (the group's full span charged even early in the prompt;
    Q/output/weight bytes excluded — identical on both paths)."""
    if fused:
        return int(group_view_bytes + chunk_bytes)
    return int(3 * group_view_bytes + chunk_bytes)
