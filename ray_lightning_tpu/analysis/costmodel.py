"""Per-topology interconnect/HBM cost model for tracecheck.

tracecheck (analysis/tracecheck.py) turns a jitted train step into a
collective schedule; this module turns that schedule into bytes-on-wire
and a latency estimate for a NAMED topology ("v5p-64") — zero hardware,
so the numbers are a *model*, not a measurement. The HBM side reuses the
planner's hardware table (`parallel.plan.hbm_bytes_for_kind`), keeping
one source of truth for per-chip memory; the ICI side adds the
bandwidth/latency figures the planner never needed.

Model assumptions (documented in docs/STATIC_ANALYSIS.md):

  * bandwidth figures are the PUBLISHED aggregate ICI bytes/s per chip
    (all links combined). Ring algorithms use every link of the group's
    torus dimension, so charging the aggregate is the optimistic bound;
    contention with other collectives is not modeled;
  * collective wire cost per chip follows the standard ring algebra over
    group size n: all_gather / reduce_scatter move (n-1)/n of the full
    payload, an all_reduce (psum) is reduce_scatter + all_gather =
    2(n-1)/n, a ppermute moves exactly its payload one hop, all_to_all
    moves (n-1)/n;
  * latency = hops x per-hop ICI latency + wire_bytes / bandwidth, with
    hops = n-1 for ring collectives and 1 for a neighbor permute;
  * DCN (multi-slice) is out of scope: tracecheck audits one slice, the
    mesh layer already refuses meshes whose non-data axes span slices
    (parallel/mesh.py order_devices_for_slices);
  * the overlap model (`compute_time_us`, consumed by tracecheck's
    hidden-vs-exposed classification): a scanned body's per-trip compute
    window is its counted matmul FLOPs (dot_general only — pallas
    kernels and elementwise work are NOT counted, an undercount that
    makes the hidden fraction conservative) over the chip's spec-sheet
    peak derated by MXU_EFFICIENCY. A prefetch-scheduled collective is
    hidden up to that window; what does not fit stays exposed.
"""
from __future__ import annotations

import dataclasses
import math
import re
from typing import Dict, Mapping, Optional, Tuple

from ray_lightning_tpu.parallel.plan import hbm_bytes_for_kind

__all__ = [
    "Topology", "CollectiveCost", "ICI_SPECS", "MXU_EFFICIENCY",
    "parse_topology", "topology_for_kind", "collective_cost",
    "compute_time_us",
]

#: ICI spec sheet per device family: (device_kind for the HBM table,
#: aggregate ICI GB/s per chip, per-hop latency in microseconds).
#: Bandwidths are the public per-chip interconnect figures (v4 2400
#: Gbps, v5e 1600, v5p 4800, v6e 3584); "cpu" is the CI pseudo-family
#: (loopback, spec-sheet-free) so tests and laptops can run the same
#: code path with an explicit hbm override.
ICI_SPECS: Dict[str, Tuple[str, float, float]] = {
    "v3": ("TPU v3", 280.0, 1.5),
    "v4": ("TPU v4", 300.0, 1.0),
    "v5e": ("TPU v5e", 200.0, 1.0),
    "v5litepod": ("TPU v5 lite", 200.0, 1.0),
    "v5p": ("TPU v5p", 600.0, 1.0),
    "v6e": ("TPU v6e", 448.0, 1.0),
    "cpu": ("cpu", 10.0, 10.0),
}

#: device_kind -> family, for topology_for_kind (the reverse lookup of
#: ICI_SPECS' first column)
_KIND_TO_FAMILY = {kind: fam for fam, (kind, _, _) in ICI_SPECS.items()}

#: fallback HBM for families the planner table doesn't know (the "cpu"
#: pseudo-family): enough to trace, small enough that a real model's
#: HBM-OVERCOMMIT check still exercises on CI
_CPU_HBM_BYTES = 16 * 1024**3


@dataclasses.dataclass(frozen=True)
class Topology:
    """One named slice: chip kind + count + interconnect figures."""

    name: str             # e.g. "v5p-64"
    device_kind: str      # PJRT device_kind string, keys the HBM table
    n_devices: int
    ici_gbps: float       # aggregate ICI bandwidth per chip, GB/s
    ici_hop_latency_us: float
    hbm_bytes: int        # usable HBM per chip
    #: spec-sheet peak bf16 TFLOP/s per chip — the compute side of the
    #: overlap model's roofline. None resolves from device_kind via the
    #: utils/probe.py table (one source of truth), so a directly
    #: constructed Topology prices compute the same as parse_topology.
    peak_tflops: Optional[float] = None

    def __post_init__(self) -> None:
        if self.peak_tflops is None:
            object.__setattr__(
                self, "peak_tflops", _peak_tflops(self.device_kind))

    @property
    def hbm_gib(self) -> float:
        return self.hbm_bytes / 1024**3

    def describe(self) -> str:
        return (f"{self.name}: {self.n_devices}x {self.device_kind} "
                f"({self.hbm_gib:.0f} GiB HBM, {self.ici_gbps:.0f} GB/s "
                "ICI per chip)")


def parse_topology(name: str, *,
                   hbm_bytes: Optional[int] = None) -> Topology:
    """``"v5p-64"`` -> a Topology. The family keys ICI_SPECS; the chip
    count is the part after the dash. Unknown families raise listing the
    known ones (same first-contact contract as hbm_bytes_for_kind)."""
    m = re.fullmatch(r"([a-z0-9]+?)-(\d+)", name.strip().lower())
    if not m:
        raise ValueError(
            f"cannot parse topology {name!r}; expected <family>-<chips> "
            f"like 'v5p-64' (families: {sorted(ICI_SPECS)})")
    family, count = m.group(1), int(m.group(2))
    if family not in ICI_SPECS:
        raise ValueError(
            f"unknown topology family {family!r} (known: "
            f"{sorted(ICI_SPECS)}); pass hbm_bytes= and use "
            "topology_for_kind for other hardware")
    if count < 1:
        raise ValueError(f"topology {name!r} must have >= 1 chip")
    kind, gbps, lat = ICI_SPECS[family]
    if hbm_bytes is None:
        try:
            hbm_bytes = hbm_bytes_for_kind(kind)
        except ValueError:  # the "cpu" pseudo-family
            hbm_bytes = _CPU_HBM_BYTES
    return Topology(name=name, device_kind=kind, n_devices=count,
                    ici_gbps=gbps, ici_hop_latency_us=lat,
                    hbm_bytes=int(hbm_bytes))


def topology_for_kind(device_kind: str, n_devices: int, *,
                      hbm_bytes: Optional[int] = None) -> Topology:
    """Topology from a PJRT ``device_kind`` string (the plan CLI's
    --device-kind vocabulary) instead of a family-dash-count name.
    Unknown kinds get the cpu pseudo-family's conservative ICI figures —
    the HBM side still honors ``hbm_bytes`` or the planner table."""
    family = _KIND_TO_FAMILY.get(device_kind, "cpu")
    _, gbps, lat = ICI_SPECS[family]
    if hbm_bytes is None:
        try:
            hbm_bytes = hbm_bytes_for_kind(device_kind)
        except ValueError:
            hbm_bytes = _CPU_HBM_BYTES
    return Topology(name=f"{family}-{n_devices}", device_kind=device_kind,
                    n_devices=n_devices, ici_gbps=gbps,
                    ici_hop_latency_us=lat, hbm_bytes=int(hbm_bytes))


def _peak_tflops(device_kind: str) -> float:
    """Spec-sheet peak for the overlap roofline — one source of truth
    with the bench/doctor probe (utils/probe.py); unknown kinds get the
    v5e-class fallback, same contract as the probe."""
    from ray_lightning_tpu.utils.probe import device_peak_tflops

    return float(device_peak_tflops(device_kind))


#: fraction of spec-sheet peak a well-tuned matmul-dominated step
#: actually sustains — the compute window for hiding collectives is
#: charged at peak x efficiency. 0.6 is the repo's own measured MFU
#: band at the flagship shapes (BENCH_r03: 0.59 best); a HIGHER
#: efficiency would shrink the window and under-claim hiding, a lower
#: one would over-claim. Documented in docs/STATIC_ANALYSIS.md.
MXU_EFFICIENCY = 0.6


def compute_time_us(flops: float, topo: Topology) -> float:
    """Time to execute ``flops`` per-device FLOPs on one chip of
    ``topo`` at the derated roofline — the overlap model's per-trip
    compute window."""
    if flops <= 0:
        return 0.0
    return flops / (topo.peak_tflops * 1e12 * MXU_EFFICIENCY) * 1e6


@dataclasses.dataclass(frozen=True)
class CollectiveCost:
    wire_bytes: int   # bytes each chip puts on ICI for this collective
    time_us: float    # ring-model latency estimate


def collective_cost(
    kind: str,
    payload_bytes: int,
    axis_sizes: Mapping[str, int],
    topo: Topology,
) -> CollectiveCost:
    """Ring-model wire bytes + latency for ONE collective.

    ``payload_bytes`` is the per-chip payload the jaxpr shows: the local
    operand bytes for psum/ppermute/all_to_all/reduce_scatter, and the
    per-chip FULL (post-gather) bytes for all_gather. ``axis_sizes`` maps
    the participating mesh axes to their sizes; the group size is their
    product."""
    n = max(1, math.prod(axis_sizes.values()))
    if n == 1:
        return CollectiveCost(0, 0.0)
    frac = (n - 1) / n
    if kind == "psum":
        wire = 2.0 * payload_bytes * frac
        hops = 2 * (n - 1)
    elif kind in ("all_gather", "reduce_scatter", "all_to_all"):
        wire = payload_bytes * frac
        hops = n - 1
    elif kind == "ppermute":
        wire = float(payload_bytes)
        hops = 1
    else:  # pmax/pmin/pbroadcast and friends: all_reduce-shaped
        wire = 2.0 * payload_bytes * frac
        hops = 2 * (n - 1)
    time_us = (wire / (topo.ici_gbps * 1e3)
               + hops * topo.ici_hop_latency_us)
    return CollectiveCost(int(wire), time_us)
