"""lockwatch — runtime lock-order sanitizer (the dynamic half of
threadcheck).

``san_lock(name)`` is a drop-in ``threading.Lock`` factory the
package's subsystems use for every long-lived lock. Disarmed (the
default), it returns a *plain* ``threading.Lock``/``RLock`` — zero
wrapper, zero overhead, decided once at creation time. Under
``RLT_LOCKWATCH=1`` it returns a ``_SanLock`` that, on every
acquisition:

* records the per-thread stack of held san-locks,
* adds edges held-lock -> acquiring-lock to a process-global order
  graph and reports a **RLT702** finding the moment a cycle appears
  (the deadlock is diagnosed from ONE execution order — the opposite
  interleaving never has to happen),
* raises instead of deadlocking on a same-thread re-acquire of a
  non-reentrant lock,
* reports **RLT705** when a lock was held longer than
  ``RLT_LOCKWATCH_MAX_HOLD_S`` seconds (default: off).

Lock identity is the NAME, not the instance: every per-request
``san_lock("serve.driver.batch")`` is one node in the order graph, the
way kernel lockdep classes locks — orders must hold for the class, not
for the specific object the test happened to build.

Findings reuse the analysis Finding schema (rule ids RLT702/RLT705), so
the suite's sanitizer report and the static threadcheck report read the
same. ``tests/conftest.py`` arms the watcher for the whole tier-1 suite
and fails the session on any recorded cycle.

``threading.Condition(san_lock(...))`` works: ``_SanLock`` implements
the ``_is_owned``/``_release_save``/``_acquire_restore`` protocol
Condition probes for, with bookkeeping kept consistent across
``wait()`` (the wait window does not count toward held-too-long — the
lock really is released).
"""
from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple, Union

from ray_lightning_tpu.analysis.findings import Finding

__all__ = [
    "san_lock", "lockwatch_armed", "lockwatch_findings",
    "lockwatch_cycles", "reset_lockwatch", "assert_lockwatch_clean",
]

# process-global sanitizer state; _META is a plain lock (the watcher
# must not watch itself)
_META = threading.Lock()
#: order graph: name -> {successor-name: "file:line" of first sighting}
_ORDER: Dict[str, Dict[str, str]] = {}
_FINDINGS: List[Finding] = []
_CYCLES: List[Tuple[str, ...]] = []
_TLS = threading.local()


def lockwatch_armed() -> bool:
    return os.environ.get("RLT_LOCKWATCH", "") not in ("", "0")


def san_lock(name: str, reentrant: bool = False):
    """A named lock. Disarmed: a raw threading.Lock/RLock (decided at
    creation — arm the env var before the module creating the lock is
    imported). Armed: an order-watching wrapper."""
    if not lockwatch_armed():
        return threading.RLock() if reentrant else threading.Lock()
    return _SanLock(name, reentrant=reentrant)


def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def _site(depth: int = 2) -> str:
    """Caller's file:line, skipping lockwatch frames."""
    try:
        f = sys._getframe(depth)
        while f is not None and f.f_code.co_filename == __file__:
            f = f.f_back
        if f is None:
            return "<unknown>"
        return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
    except Exception:  # pragma: no cover - _getframe always exists on CPython
        return "<unknown>"


def _find_cycle(start: str, goal: str) -> Optional[Tuple[str, ...]]:
    """Path start ->* goal in _ORDER (callers hold _META)."""
    seen: Set[str] = set()
    path: List[str] = []

    def dfs(n: str) -> bool:
        if n == goal:
            path.append(n)
            return True
        if n in seen:
            return False
        seen.add(n)
        for m in _ORDER.get(n, ()):
            if dfs(m):
                path.append(n)
                return True
        return False

    return tuple(reversed(path)) if dfs(start) else None


def _record(finding: Finding) -> None:
    with _META:
        _FINDINGS.append(finding)


class _HeldEntry:
    __slots__ = ("lock", "t0", "depth", "site")

    def __init__(self, lock: "_SanLock", site: str):
        self.lock = lock
        self.t0 = time.monotonic()
        self.depth = 1
        self.site = site


class _SanLock:
    """Order-watching lock wrapper; see module docstring."""

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()
        hold = os.environ.get("RLT_LOCKWATCH_MAX_HOLD_S", "")
        try:
            self.max_hold_s: Optional[float] = float(hold) if hold else None
        except ValueError:
            self.max_hold_s = None

    def __repr__(self):
        return f"<san_lock {self.name!r} reentrant={self.reentrant}>"

    # ---- lock protocol ---------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        st = _stack()
        mine = next((e for e in st if e.lock is self), None)
        site = _site()
        if mine is not None and not self.reentrant:
            _record(Finding(
                rule="RLT702",
                message=(f"same-thread re-acquire of non-reentrant lock "
                         f"`{self.name}` (first taken at {mine.site}) — "
                         f"this would deadlock; lockwatch raised instead"),
                file=site.split(":")[0], line=_int_line(site),
                symbol=self.name))
            raise RuntimeError(
                f"lockwatch: thread {threading.current_thread().name} "
                f"re-acquired non-reentrant san_lock({self.name!r}) "
                f"(first taken at {mine.site})")
        if mine is None:
            self._note_edges(st, site)
        ok = self._inner.acquire(blocking, timeout) if timeout != -1 \
            else self._inner.acquire(blocking)
        if not ok:
            return False
        if mine is not None:
            mine.depth += 1
        else:
            st.append(_HeldEntry(self, site))
        return True

    def release(self) -> None:
        st = _stack()
        mine = next((e for e in reversed(st) if e.lock is self), None)
        if mine is not None:
            mine.depth -= 1
            if mine.depth == 0:
                st.remove(mine)
                held = time.monotonic() - mine.t0
                if self.max_hold_s is not None and held > self.max_hold_s:
                    _record(Finding(
                        rule="RLT705",
                        message=(f"lock `{self.name}` held for "
                                 f"{held:.3f}s (> RLT_LOCKWATCH_MAX_HOLD_S="
                                 f"{self.max_hold_s}) — acquired at "
                                 f"{mine.site}"),
                        severity="warning", symbol=self.name))
        self._inner.release()

    def locked(self) -> bool:
        inner = self._inner
        return inner.locked() if hasattr(inner, "locked") else False

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    # ---- Condition protocol ----------------------------------------------

    def _is_owned(self) -> bool:
        return any(e.lock is self for e in _stack())

    def _release_save(self):
        """Condition.wait: fully release (even a reentrant depth>1 hold);
        returns the depth to restore."""
        st = _stack()
        mine = next((e for e in reversed(st) if e.lock is self), None)
        depth = mine.depth if mine is not None else 1
        for _ in range(depth):
            self.release()
        return depth

    def _acquire_restore(self, depth) -> None:
        for _ in range(depth):
            self.acquire()

    # ---- order graph ------------------------------------------------------

    def _note_edges(self, st: list, site: str) -> None:
        held_names = []
        for e in st:
            if e.lock.name != self.name and e.lock.name not in held_names:
                held_names.append(e.lock.name)
        if not held_names:
            return
        with _META:
            for h in held_names:
                succ = _ORDER.setdefault(h, {})
                if self.name in succ:
                    continue
                # new edge h -> self: a cycle exists iff self already
                # reaches h
                cycle = _find_cycle(self.name, h)
                succ[self.name] = site
                if cycle is None:
                    continue
                key = tuple(sorted(set(cycle)))
                if any(tuple(sorted(set(c))) == key for c in _CYCLES):
                    continue
                _CYCLES.append(cycle)
                hops = " -> ".join(cycle + (cycle[0],))
                _FINDINGS.append(Finding(
                    rule="RLT702",
                    message=(f"runtime lock-order cycle observed: {hops} "
                             f"(edge `{h}` -> `{self.name}` closed the "
                             f"cycle at {site}) — the opposite "
                             f"interleaving deadlocks"),
                    symbol=self.name))


def _int_line(site: str) -> Optional[int]:
    try:
        return int(site.rsplit(":", 1)[1])
    except (IndexError, ValueError):
        return None


# ---- reporting API ---------------------------------------------------------

def lockwatch_findings() -> List[Finding]:
    with _META:
        return list(_FINDINGS)


def lockwatch_cycles() -> List[Tuple[str, ...]]:
    with _META:
        return list(_CYCLES)


def reset_lockwatch() -> None:
    """Clear the order graph and findings (test isolation)."""
    with _META:
        _ORDER.clear()
        _FINDINGS.clear()
        _CYCLES.clear()


def assert_lockwatch_clean() -> None:
    """Raise AssertionError when any lock-order cycle was observed."""
    cycles = lockwatch_cycles()
    if cycles:
        lines = "\n".join(
            f.format() for f in lockwatch_findings() if f.rule == "RLT702")
        raise AssertionError(
            f"lockwatch observed {len(cycles)} lock-order cycle(s):\n"
            f"{lines}")
