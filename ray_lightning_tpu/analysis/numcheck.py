"""numcheck — jaxpr-level mixed-precision flow auditor (RLT8xx).

The analysis stack audits sharding (RLT1xx), traced-code hygiene
(RLT2xx), collectives/HBM (RLT3xx), and host concurrency (RLT7xx);
this module adds the NUMERICS layer: a dtype-provenance pass over the
same jaxpr tracecheck walks (recursing into pjit/scan/cond/remat/
shard_map/pallas_call), emitting RLT801-805 through the shared Finding
vocabulary. docs/STATIC_ANALYSIS.md "numcheck — the precision layer"
is the prose companion (dtype model, sanction rationale, known limits).

The dtype model (what each rule PROVES, and what it sanctions):

  * RLT801 low-precision-accumulation — a `dot_general` whose OUTPUT
    dtype is bf16/f16 (no ``preferred_element_type=f32``), or a
    `reduce_sum`/`cumsum` over a bf16/f16 operand, with contraction/
    reduction extent > `LOW_PRECISION_EXTENT`. Each bf16 add keeps 8
    mantissa bits; a K-term sum loses ~log2(K) of them. The MXU does
    accumulate a single dot in f32 internally, but a bf16 OUTPUT
    rounds that accumulator away at the op boundary — the repo's
    policy (ops/fused_ce.py, ops/pallas/*) is the explicit preferred
    f32 + one rounding, which this rule enforces. Small extents are
    sanctioned: the error is bounded by the extent.
  * RLT802 unstable-primitive-in-low-precision — exp/exp2/log/rsqrt
    (the softmax / logsumexp / variance building blocks) on a bf16/f16
    operand. Sanctions: an exp whose operand is max-subtracted (the
    ``x - reduce_max(x)`` provenance is tracked through layout ops) is
    the guarded softmax form and never flagged; the pallas kernels'
    f32 scratch is sanctioned by construction — their scores come out
    of preferred-f32 dots, so the exp/log operands the walk sees are
    already f32. Bounded primitives (sigmoid/tanh) are well-
    conditioned in bf16 and out of scope.
  * RLT803 cast-churn — an f32 value rounded to bf16/f16 and converted
    straight back to f32 with only layout ops (reshape/transpose/
    broadcast/slice/...) or a scan-carry boundary in between. Priced
    in wasted HBM bytes (the pointless narrow copy is written and read
    back) via the shared width table. Two sanctioned shapes: (a) round
    trips whose two converts live in DIFFERENT source files — the
    custom_vjp cotangent seam (jax rounds cotangents to the primal's
    dtype at each function boundary), which the caller cannot remove
    without changing the primal dtype contract; (b) rounding a fresh
    WIDE ACCUMULATOR (a dot output wider than an operand) — that is
    RLT801's own prescription (`preferred_element_type=f32`, round
    once after), so the downcast opens no round trip even when AD's
    transpose later re-widens the cotangent at the same site.
  * RLT804 low-precision-gradient-collective — a psum/reduce_scatter
    event whose payload dtype is bf16/f16 while the optimizer state of
    the matched parameter is stored wider. Judged over tracecheck's
    CollectiveEvent stream (gradient reductions under FSDP/DP are
    GSPMD-inserted — they exist only as events, never as jaxpr eqns)
    with widths from the SAME `costmodel.DTYPE_WIDTHS` table
    plan_checker's RLT105 reads, so the two rules cannot drift.
  * RLT805 quant-contract — the rule the int8-KV campaign (ROADMAP
    item 2c) compiles against. Every int8/int4-valued var (and every
    float var converted FROM one — an unscaled dequant) carries a
    `quant` flag; a multiply/divide by an f32-or-wider float operand
    clears it (the dequantization scale was applied); float arithmetic
    (dot/add/sub/reduce_sum) on a still-flagged value fires, as does a
    scale narrower than f32. Integer arithmetic on int8 (the proper
    int8xint8->int32 GEMM shape) keeps the flag without firing —
    the contract is judged where the value re-enters float math.
    uint8 is deliberately NOT tracked: it is overwhelmingly image/byte
    payload, not scaled-quantized data.

Known limits (documented, test-pinned where cheap): provenance does
not cross a pallas kernel boundary (kernel outputs restart from their
own dtype); `cond` merges branch flags optimistically (a sanction in
any branch sanctions the merged value); the scale-clearing rule cannot
distinguish a real dequant scale from any other multiply — forgiving
by design.

The module also hosts the STATIC (AST) numerics mini-pass behind
``lint --numerics``: single-expression patterns only — an
``.astype(bf16/f16)`` operand inline in a jnp.dot/matmul/einsum/
lax.dot_general call without ``preferred_element_type`` (RLT801), or
an inline ``.astype(int8/int4)`` operand (RLT805). Same
``# rlt: disable=`` suppression as every other AST rule.
"""
from __future__ import annotations

import dataclasses
import math
import os
from typing import (
    Any, Dict, List, Mapping, Optional, Sequence, Tuple,
)

from ray_lightning_tpu.analysis.costmodel import dtype_width
from ray_lightning_tpu.analysis.findings import Finding

__all__ = [
    "LOW_PRECISION_EXTENT", "numcheck_jaxpr",
    "check_gradient_collectives", "check_numerics_sources",
    "check_numerics_paths", "summarize",
]

#: contraction/reduction extents at or below this are sanctioned for
#: RLT801: a K-term bf16 sum loses ~log2(K) of its 8 mantissa bits, so
#: 256 terms cost at most one decimal digit — the point where the
#: rounding stops being noise. Above it (the 4096-wide model dims, the
#: quarter-million-token wgrad contractions) the accumulator must be
#: f32.
LOW_PRECISION_EXTENT = 256

_LOW_FLOAT = frozenset({"bfloat16", "float16"})
_QUANT_INT = frozenset({"int8", "int4", "uint4"})
_FLOAT_NAMES = frozenset({
    "bfloat16", "float16", "float32", "float64",
    "float8_e4m3fn", "float8_e5m2", "float8_e4m3b11fnuz",
})

#: ops that move/relabel bytes without arithmetic: dtype provenance
#: (cast_from / submax / is_max / quant) rides through them unchanged
_CARRIES_PROVENANCE = frozenset({
    "reshape", "transpose", "broadcast_in_dim", "squeeze",
    "expand_dims", "rev", "copy", "slice", "dynamic_slice", "gather",
    "sharding_constraint", "name", "reduce_precision", "pad",
    "stop_gradient", "real", "imag", "neg",
})

#: sub-jaxpr call-like primitives and where their jaxpr hides — the
#: same recursion set tracecheck's walker owns
_CALL_PARAM_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr")
_CALL_PRIMS = frozenset({
    "pjit", "closed_call", "core_call", "custom_vjp_call",
    "custom_vjp_call_jaxpr", "custom_jvp_call", "remat2", "checkpoint",
    "custom_lin",
})


def _is_float(name: str) -> bool:
    return name in _FLOAT_NAMES


def _width(name: str) -> float:
    return dtype_width(name) or 0.0


def _dtype_of(aval) -> str:
    """Dtype name of an aval — follows pallas `Ref` avals to their
    inner aval so kernel interiors audit like plain arrays."""
    dt = getattr(aval, "dtype", None)
    if dt is None:
        dt = getattr(getattr(aval, "inner_aval", None), "dtype", None)
    return str(dt) if dt is not None else "opaque"


def _size_of(aval) -> int:
    shape = getattr(aval, "shape", None)
    if shape is None:
        shape = getattr(getattr(aval, "inner_aval", None), "shape", ())
    return int(math.prod(shape or (1,)))


def _fmt_mib(n: float) -> str:
    return f"{n / (1024 ** 2):.1f} MiB"


def _src_file(src: Optional[str]) -> Optional[str]:
    """File component of a "prim @ file.py:line" source string."""
    if not src or " @ " not in src:
        return None
    return src.split(" @ ", 1)[1].rsplit(":", 1)[0]


@dataclasses.dataclass
class _VInfo:
    """Per-var numeric provenance.

    ``widest`` is the (width, dtype-name) of the widest FLOAT dtype on
    the value's provenance path — the loss's entry is the report's
    "widest-path dtype". ``cast_from`` names the wider float this value
    was rounded down from, surviving layout ops only (any arithmetic
    clears it — the round trip then bought a real narrower compute).
    ``is_max``/``submax`` track the ``x - reduce_max(x)`` softmax guard.
    ``quant`` is the RLT805 contract flag (see module docstring)."""

    widest: Tuple[float, str]
    cast_from: Optional[str] = None
    #: source of the downcast that set ``cast_from`` — names the other
    #: end of the round trip in the RLT803 message
    cast_src: Optional[str] = None
    submax: bool = False
    is_max: bool = False
    quant: bool = False
    #: output of a dot_general wider than at least one float operand —
    #: a fresh accumulator. Rounding it once is RLT801's RECOMMENDED
    #: shape (`preferred_element_type=f32`, round after), so that
    #: downcast never opens an RLT803 round trip: its complementary
    #: upcast (often jax's AD transpose re-widening the cotangent) is
    #: the unavoidable other half of the sanctioned design.
    acc_wide: bool = False


def _info_for(aval) -> _VInfo:
    dt = _dtype_of(aval)
    w = _width(dt) if _is_float(dt) else 0.0
    return _VInfo(widest=(w, dt if w else ""), quant=dt in _QUANT_INT)


class _NumAuditor:
    """Single-use dtype-provenance walker. Mirrors tracecheck's
    recursion structure but carries numeric state instead of sharding
    state; findings dedupe by (rule, source) so loop trips and repeated
    walks (scan fixpoints) report one finding per site."""

    def __init__(self):
        self._findings: Dict[Tuple, Finding] = {}
        self._quiet = 0

    # ---- plumbing -------------------------------------------------------

    @property
    def findings(self) -> List[Finding]:
        return list(self._findings.values())

    def flag(self, rule: str, message: str, *, source: str) -> None:
        if self._quiet:
            return
        key = (rule, source)
        if key not in self._findings:
            self._findings[key] = Finding(
                rule, f"{message} [at {source}]", symbol=source)

    @staticmethod
    def _src(eqn) -> str:
        name = eqn.primitive.name
        try:
            from jax._src import source_info_util

            frame = source_info_util.user_frame(eqn.source_info)
            if frame is not None:
                base = os.path.basename(frame.file_name)
                if base == "tracecheck.py":
                    return f"{name} @ <train-step optimizer update>"
                return f"{name} @ {base}:{frame.start_line}"
        except Exception:  # noqa: BLE001 — provenance is best-effort
            pass
        return name

    def _read(self, env: Dict, v) -> _VInfo:
        if not hasattr(v, "count"):  # Literal
            return _info_for(getattr(v, "aval", None))
        got = env.get(v)
        if got is None:
            return _info_for(getattr(v, "aval", None))
        return got

    # ---- the walk -------------------------------------------------------

    def walk(self, jaxpr, env: Dict) -> None:
        for eqn in jaxpr.eqns:
            try:
                self._process(eqn, env)
            except Exception:  # noqa: BLE001 — numerics auditing must
                # degrade, never abort the audit: unknown structure ->
                # default (dtype-only) provenance for the outputs
                for v in eqn.outvars:
                    if hasattr(v, "count"):
                        env[v] = _info_for(getattr(v, "aval", None))

    def _seed_and_walk(self, closed_or_open, in_infos: Sequence[_VInfo],
                       ) -> Tuple[Dict, List[_VInfo]]:
        inner = getattr(closed_or_open, "jaxpr", closed_or_open)
        sub_env: Dict = {}
        for iv, info in zip(inner.invars, in_infos):
            sub_env[iv] = info
        for iv in inner.invars[len(in_infos):]:
            sub_env[iv] = _info_for(getattr(iv, "aval", None))
        for cv in inner.constvars:
            sub_env[cv] = _info_for(getattr(cv, "aval", None))
        self.walk(inner, sub_env)
        outs = [self._read(sub_env, ov) for ov in inner.outvars]
        return sub_env, outs

    # ---- helpers --------------------------------------------------------

    def _default_out(self, ins: Sequence[_VInfo], aval) -> _VInfo:
        out = _info_for(aval)
        for i in ins:
            if i.widest[0] > out.widest[0]:
                out.widest = i.widest
        return out

    def _consume_quant(self, eqn, ins, src) -> None:
        """RLT805 fire point: a still-flagged FLOAT value reaches
        arithmetic — the dequant scale was never applied."""
        for v, info in zip(eqn.invars, ins):
            dt = _dtype_of(getattr(v, "aval", None))
            if info.quant and _is_float(dt):
                self.flag(
                    "RLT805",
                    f"an int8/int4-origin value (now {dt}) is consumed "
                    f"by {eqn.primitive.name} with no dequantization "
                    "scale applied: multiply by the f32 scale between "
                    "the integer load and the math",
                    source=src)
                return

    # ---- per-primitive dispatch -----------------------------------------

    def _process(self, eqn, env: Dict) -> None:
        name = eqn.primitive.name
        ins = [self._read(env, v) for v in eqn.invars]
        out = [v for v in eqn.outvars]
        src = self._src(eqn)

        def set_all(infos: Sequence[_VInfo]) -> None:
            for v, info in zip(out, infos):
                if hasattr(v, "count"):
                    env[v] = info

        def set_default() -> None:
            set_all([self._default_out(ins, getattr(v, "aval", None))
                     for v in out])

        if name == "convert_element_type":
            set_all([self._convert(eqn, ins[0], src)])
        elif name in _CARRIES_PROVENANCE:
            base = ins[0] if ins else _info_for(
                getattr(out[0], "aval", None))
            info = self._default_out(ins, getattr(out[0], "aval", None))
            info.cast_from = base.cast_from
            info.cast_src = base.cast_src
            info.submax = base.submax
            info.is_max = base.is_max
            info.quant = base.quant
            info.acc_wide = base.acc_wide
            set_all([dataclasses.replace(info) for _ in out])
        elif name in ("concatenate", "dynamic_update_slice", "scatter",
                      "scatter-add", "scatter_add", "select_n"):
            # value merges: flags combine forgivingly (a sanction on any
            # piece sanctions the merge), quant pessimistically (any
            # unscaled piece keeps the contract open)
            cases = ins[1:] if name == "select_n" else ins
            cases = cases or ins
            info = self._default_out(ins, getattr(out[0], "aval", None))
            info.quant = any(i.quant for i in cases)
            info.is_max = any(i.is_max for i in cases)
            info.submax = any(i.submax for i in cases)
            cf = {i.cast_from for i in cases}
            info.cast_from = cf.pop() if len(cf) == 1 else None
            info.cast_src = next(
                (i.cast_src for i in cases if i.cast_src), None) \
                if info.cast_from else None
            set_all([dataclasses.replace(info) for _ in out])
        elif name in ("reduce_max", "argmax"):
            info = self._default_out(ins, getattr(out[0], "aval", None))
            info.is_max = True
            set_all([info])
        elif name == "max":
            info = self._default_out(ins, getattr(out[0], "aval", None))
            info.is_max = any(i.is_max for i in ins)
            set_all([info])
        elif name == "sub":
            self._consume_quant(eqn, ins, src)
            info = self._default_out(ins, getattr(out[0], "aval", None))
            info.submax = len(ins) > 1 and ins[1].is_max
            set_all([info])
        elif name in ("add", "add_any"):
            self._consume_quant(eqn, ins, src)
            set_default()
        elif name in ("mul", "div"):
            set_all([self._scale(eqn, ins, src)])
        elif name in ("exp", "exp2"):
            op_dt = _dtype_of(getattr(eqn.invars[0], "aval", None))
            if (op_dt in _LOW_FLOAT and not ins[0].submax):
                self.flag(
                    "RLT802",
                    f"{name} on a {op_dt} operand with no upcast and no "
                    "max-subtraction: exp overflows bf16 beyond ~88 — "
                    "subtract the row max first (softmax form) or "
                    "compute in f32",
                    source=src)
            set_default()
        elif name in ("log", "rsqrt"):
            op_dt = _dtype_of(getattr(eqn.invars[0], "aval", None))
            if op_dt in _LOW_FLOAT:
                self.flag(
                    "RLT802",
                    f"{name} on a {op_dt} operand with no f32 upcast: "
                    "the low-order bits this primitive lives on are "
                    "already rounded away",
                    source=src)
            set_default()
        elif name == "dot_general":
            self._consume_quant(eqn, ins, src)
            out_dt = _dtype_of(getattr(out[0], "aval", None))
            (lc, _), _ = eqn.params["dimension_numbers"]
            lshape = getattr(getattr(eqn.invars[0], "aval", None),
                             "shape", ())
            extent = int(math.prod([lshape[d] for d in lc] or [1]))
            if out_dt in _LOW_FLOAT and extent > LOW_PRECISION_EXTENT:
                self.flag(
                    "RLT801",
                    f"dot_general accumulates {extent} products into a "
                    f"{out_dt} output (no preferred_element_type=f32): "
                    f"~{math.log2(extent):.0f} of its 8 mantissa bits "
                    "are rounding noise — set "
                    "preferred_element_type=jnp.float32 and round once "
                    "after",
                    source=src)
            info = self._default_out(ins, getattr(out[0], "aval", None))
            info.quant = any(i.quant for i in ins)
            if _is_float(out_dt):
                op_widths = [
                    _width(_dtype_of(getattr(v, "aval", None)))
                    for v in eqn.invars
                    if _is_float(_dtype_of(getattr(v, "aval", None)))]
                info.acc_wide = bool(
                    op_widths and _width(out_dt) > min(op_widths))
            set_all([info])
        elif name in ("reduce_sum", "cumsum"):
            self._consume_quant(eqn, ins, src)
            op_aval = getattr(eqn.invars[0], "aval", None)
            op_dt = _dtype_of(op_aval)
            shape = getattr(op_aval, "shape", ())
            if name == "cumsum":
                axis = eqn.params.get("axis", 0)
                extent = int(shape[axis]) if shape else 1
            else:
                axes = eqn.params.get("axes", ())
                extent = int(math.prod(
                    [shape[a] for a in axes] or [1]))
            if op_dt in _LOW_FLOAT and extent > LOW_PRECISION_EXTENT:
                self.flag(
                    "RLT801",
                    f"{name} over {extent} {op_dt} terms accumulates in "
                    f"{op_dt}: upcast the operand (or use a dot with "
                    "preferred_element_type=f32) so the accumulator is "
                    "f32",
                    source=src)
            set_default()
        elif name == "scan":
            self._scan(eqn, ins, env)
        elif name == "while":
            self._while(eqn, ins, env)
        elif name == "cond":
            self._cond(eqn, ins, env)
        elif name == "shard_map":
            _, outs = self._seed_and_walk(eqn.params["jaxpr"], ins)
            set_all(outs)
        elif name == "pallas_call":
            # kernel interiors audit like plain code (Ref reads restart
            # from the ref's dtype — an int8 pool read re-arms the
            # quant flag); kernel OUTPUT provenance does not cross the
            # boundary back out (documented limit)
            closed = eqn.params.get("jaxpr")
            if closed is not None:
                try:
                    self._seed_and_walk(closed, ins)
                except Exception:  # noqa: BLE001 — best-effort
                    pass
            set_default()
        elif name in _CALL_PRIMS:
            closed = next((eqn.params[k] for k in _CALL_PARAM_KEYS
                           if eqn.params.get(k) is not None), None)
            if closed is None:
                set_default()
            else:
                _, outs = self._seed_and_walk(closed, ins)
                set_all(outs + [self._default_out(ins, getattr(
                    v, "aval", None)) for v in out[len(outs):]])
        elif name == "remat_opt":
            closed = eqn.params.get("fwd_jaxpr")
            if closed is None:
                set_default()
            else:
                _, outs = self._seed_and_walk(closed, ins)
                by_key: Dict[Tuple, List[_VInfo]] = {}
                inner = getattr(closed, "jaxpr", closed)
                for ov, info in zip(inner.outvars, outs):
                    key = (tuple(getattr(ov.aval, "shape", ())),
                           _dtype_of(ov.aval))
                    by_key.setdefault(key, []).append(info)
                for v in out:
                    key = (tuple(getattr(v.aval, "shape", ())),
                           _dtype_of(v.aval))
                    lst = by_key.get(key)
                    env[v] = (lst.pop(0) if lst
                              else self._default_out(ins, v.aval))
        else:
            set_default()

    # ---- convert / scale / control flow ---------------------------------

    def _convert(self, eqn, op: _VInfo, src: str) -> _VInfo:
        in_aval = getattr(eqn.invars[0], "aval", None)
        din, dout = _dtype_of(in_aval), _dtype_of(eqn.outvars[0].aval)
        win, wout = _width(din), _width(dout)
        info = self._default_out([op], eqn.outvars[0].aval)
        info.submax, info.is_max = op.submax, op.is_max
        if _is_float(din) and _is_float(dout):
            if wout < win:
                # rounding down: remember what we came from (keep an
                # even wider origin if the chain keeps narrowing) —
                # unless the value is a fresh wide accumulator: rounding
                # a dot's f32 accumulator ONCE is exactly what RLT801
                # prescribes, so that downcast opens no round trip
                if op.acc_wide:
                    pass
                elif op.cast_from and _width(op.cast_from) > win:
                    info.cast_from = op.cast_from
                    info.cast_src = op.cast_src
                else:
                    info.cast_from = din
                    info.cast_src = src
                info.quant = op.quant
            elif wout > win:
                # cross-FILE round trips are sanctioned: a cotangent
                # rounded to bf16 at one custom_vjp's output and
                # widened at the next function's input is jax's
                # cotangent-dtype convention (cotangents flow at the
                # primal's dtype across the seam) — the caller cannot
                # remove that hop without changing the primal contract.
                # Real churn has both converts in the same file.
                same_file = (_src_file(op.cast_src) == _src_file(src)
                             if op.cast_src else True)
                if (op.cast_from and wout >= _width(op.cast_from)
                        and same_file):
                    n = _size_of(in_aval)
                    wasted = n * win * 2  # narrow copy written + read
                    rounded = (f" (rounded at {op.cast_src})"
                               if op.cast_src else "")
                    self.flag(
                        "RLT803",
                        f"{op.cast_from}->{din}->{dout} round trip with "
                        f"no compute in between{rounded}: the narrow "
                        "copy buys nothing, costs a rounding, and moves "
                        f"~{_fmt_mib(wasted)} of pointless HBM traffic",
                        source=src)
                info.cast_from = None
                info.cast_src = None
                info.quant = op.quant
            else:
                info.cast_from = op.cast_from
                info.cast_src = op.cast_src
                info.quant = op.quant
        elif din in _QUANT_INT and _is_float(dout):
            # unscaled dequant: the contract stays open until a scale
            # is applied
            info.quant = True
        elif dout in _QUANT_INT:
            info.quant = True
        else:
            # int widening (int8 -> int32 index/count math) drops the
            # contract; everything else restarts from the dtype
            info.quant = dout in _QUANT_INT
        return info

    def _scale(self, eqn, ins: Sequence[_VInfo], src: str) -> _VInfo:
        info = self._default_out(ins, eqn.outvars[0].aval)
        dts = [_dtype_of(getattr(v, "aval", None)) for v in eqn.invars]
        quant = [i.quant for i in ins]
        if any(quant) and len(ins) == 2:
            other = 1 if quant[0] else 0
            if quant[0] and quant[1]:
                info.quant = True  # int8*int8 products: still unscaled
            elif _is_float(dts[other]):
                if _width(dts[other]) >= 4.0:
                    info.quant = False  # dequant scale applied
                else:
                    self.flag(
                        "RLT805",
                        f"dequantization scale is {dts[other]} — "
                        "narrower than f32: the scale re-quantizes the "
                        "error the int8 encoding already paid for; "
                        "store scales in f32",
                        source=src)
                    info.quant = False
            else:
                info.quant = True  # scaled by an int: not a dequant
        else:
            info.quant = any(quant)
        return info

    def _merge_carry(self, init: List[_VInfo],
                     outs: List[_VInfo]) -> List[_VInfo]:
        merged = []
        for a, b in zip(init, outs):
            m = dataclasses.replace(a)
            if b.widest[0] > m.widest[0]:
                m.widest = b.widest
            m.quant = a.quant or b.quant
            m.cast_from = a.cast_from or b.cast_from
            m.cast_src = (a.cast_src if a.cast_from else b.cast_src)
            m.is_max = a.is_max or b.is_max
            m.submax = a.submax or b.submax
            merged.append(m)
        return merged

    def _scan(self, eqn, ins: List[_VInfo], env: Dict) -> None:
        p = eqn.params
        closed = p["jaxpr"]
        nc, ncar = p["num_consts"], p["num_carry"]
        consts, init, xs = ins[:nc], ins[nc:nc + ncar], ins[nc + ncar:]
        self._quiet += 1
        try:
            _, outs = self._seed_and_walk(closed, consts + init + xs)
        finally:
            self._quiet -= 1
        carry = self._merge_carry(init, outs[:ncar])
        _, outs = self._seed_and_walk(closed, consts + carry + xs)
        for v, info in zip(eqn.outvars, outs[:ncar] + outs[ncar:]):
            if hasattr(v, "count"):
                env[v] = info

    def _while(self, eqn, ins: List[_VInfo], env: Dict) -> None:
        p = eqn.params
        cn, bn = p["cond_nconsts"], p["body_nconsts"]
        body = p["body_jaxpr"]
        bconsts, init = ins[cn:cn + bn], ins[cn + bn:]
        self._quiet += 1
        try:
            _, outs = self._seed_and_walk(body, bconsts + init)
        finally:
            self._quiet -= 1
        carry = self._merge_carry(init, outs)
        _, outs = self._seed_and_walk(body, bconsts + carry)
        for v, info in zip(eqn.outvars, outs):
            if hasattr(v, "count"):
                env[v] = info

    def _cond(self, eqn, ins: List[_VInfo], env: Dict) -> None:
        branches = eqn.params["branches"]
        ops = ins[1:]
        outs_by_branch = []
        for br in branches:  # every branch is real code: record all
            _, outs = self._seed_and_walk(br, ops)
            outs_by_branch.append(outs)
        merged = []
        for tup in zip(*outs_by_branch):
            m = dataclasses.replace(tup[0])
            for o in tup[1:]:
                if o.widest[0] > m.widest[0]:
                    m.widest = o.widest
                m.quant = m.quant or o.quant
                m.submax = m.submax or o.submax
                m.is_max = m.is_max or o.is_max
            merged.append(m)
        for v, info in zip(eqn.outvars, merged):
            if hasattr(v, "count"):
                env[v] = info


# --------------------------------------------------------------------------
# public API — jaxpr side
# --------------------------------------------------------------------------


def numcheck_jaxpr(closed, *, loss_index: Optional[int] = None,
                   ) -> Tuple[List[Finding], Dict[str, Any]]:
    """Audit a ClosedJaxpr (or anything with ``.jaxpr``) for RLT801/
    802/803/805 and return ``(findings, info)``. ``info`` carries
    ``loss_widest_dtype`` when ``loss_index`` names an output: the
    widest float dtype on that output's provenance path — the
    precision ledger's "is the loss math ever actually f32" answer."""
    aud = _NumAuditor()
    jaxpr = getattr(closed, "jaxpr", closed)
    env: Dict = {}
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        env[v] = _info_for(getattr(v, "aval", None))
    aud.walk(jaxpr, env)
    info: Dict[str, Any] = {}
    if loss_index is not None and 0 <= loss_index < len(jaxpr.outvars):
        ov = jaxpr.outvars[loss_index]
        vi = aud._read(env, ov)
        info["loss_widest_dtype"] = (
            vi.widest[1] or _dtype_of(getattr(ov, "aval", None)))
    return aud.findings, info


def _opt_width_by_param(named_params: Mapping[str, Any],
                        named_opt: Mapping[str, Any]) -> Dict[str, float]:
    """Max optimizer-state width per matched param path — the SAME
    longest-path-suffix + shape match plan_checker's RLT105 uses."""
    out: Dict[str, float] = {}
    for opath, oleaf in named_opt.items():
        oshape = getattr(oleaf, "shape", None)
        odtype = getattr(oleaf, "dtype", None)
        if oshape is None or odtype is None:
            continue
        parts = opath.split("/")
        for i in range(len(parts)):
            cand = "/".join(parts[i:])
            leaf = named_params.get(cand)
            if leaf is not None and getattr(leaf, "shape", ()) == oshape:
                w = dtype_width(odtype) or 0.0
                out[cand] = max(out.get(cand, 0.0), w)
                break
    return out


def check_gradient_collectives(
        events: Sequence[Any],
        named_params: Mapping[str, Any],
        named_opt: Mapping[str, Any]) -> List[Finding]:
    """RLT804 over tracecheck's CollectiveEvent stream: a psum/
    reduce_scatter whose payload dtype is bf16/f16, matched to a param
    whose optimizer state is stored wider. Width comparisons come from
    the shared `costmodel.DTYPE_WIDTHS` (single-sourced with RLT105)."""
    opt_w = _opt_width_by_param(named_params, named_opt)
    findings: List[Finding] = []
    seen = set()
    for ev in events:
        if getattr(ev, "kind", None) not in ("psum", "reduce_scatter"):
            continue
        dt = getattr(ev, "dtype", None)
        path = getattr(ev, "param_path", None)
        if dt not in _LOW_FLOAT or not path:
            continue
        ppath = path.split("/", 1)[1] if path.startswith("params/") \
            else None
        if ppath is None:
            continue
        ow = opt_w.get(ppath, 0.0)
        gw = dtype_width(dt) or 0.0
        if ow > gw:
            key = (ev.source, path)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                "RLT804",
                f"gradient {ev.kind} over {'x'.join(ev.axes)} runs on a "
                f"{dt} payload while {ppath}'s optimizer state is "
                f"stored {ow:g}-byte wide: the ring reduction "
                "accumulates in the wire dtype, losing precision "
                "before the optimizer sees the sum — widen the "
                "gradient (preferred_element_type=f32 on the backward "
                f"matmuls) [at {ev.source}]",
                symbol=path))
    return findings


def summarize(findings: Sequence[Finding]) -> dict:
    """Counts-by-rule block for bench JSON lines (backend-down safe —
    pure host-side work), mirroring concurrency.summarize."""
    by_rule: Dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return {"total": len(findings), "by_rule": dict(sorted(by_rule.items()))}


# --------------------------------------------------------------------------
# static (AST) mini-pass — `lint --numerics`
# --------------------------------------------------------------------------
#
# Single-expression window only (documented limit): the jaxpr pass is
# the real engine; this catches the copy-paste shapes reviewers meet in
# diffs — an `.astype(bf16)` pushed INLINE into a dot/einsum call
# without preferred_element_type, or an inline `.astype(int8)` operand.

_AST_DOT_CALLS = frozenset({
    "jnp.dot", "jnp.matmul", "jnp.einsum", "jnp.tensordot",
    "jax.numpy.dot", "jax.numpy.matmul", "jax.numpy.einsum",
    "lax.dot_general", "jax.lax.dot_general",
})
_AST_LOW_FLOAT = frozenset({
    "jnp.bfloat16", "jnp.float16", "jax.numpy.bfloat16",
    "jax.numpy.float16", "np.float16", "bfloat16", "float16",
})
_AST_QUANT = frozenset({
    "jnp.int8", "jnp.int4", "jax.numpy.int8", "jax.numpy.int4",
    "np.int8", "int8", "int4",
})


def _ast_dotted(node) -> Optional[str]:
    import ast

    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _astype_target(node) -> Optional[str]:
    """'jnp.bfloat16'-style dtype name when ``node`` is an
    ``<expr>.astype(<dtype>)`` call, else None."""
    import ast

    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype" and node.args):
        return None
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return _ast_dotted(arg)


def check_numerics_sources(
        sources: Sequence[Tuple[str, str]]) -> List[Finding]:
    """Run the static numerics pass over (filename, source) pairs."""
    import ast

    from ray_lightning_tpu.analysis.linter import _FileLint

    out: List[Finding] = []
    for filename, source in sources:
        try:
            tree = ast.parse(source)
        except SyntaxError:
            continue  # the shardcheck linter owns RLT001
        lint = _FileLint(source, filename)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _ast_dotted(node.func)
            if callee not in _AST_DOT_CALLS:
                continue
            has_pref = any(kw.arg == "preferred_element_type"
                           for kw in node.keywords)
            for arg in node.args:
                dt = _astype_target(arg)
                if dt is None:
                    continue
                if dt in _AST_LOW_FLOAT and not has_pref:
                    lint.add(
                        "RLT801",
                        f"{callee} consumes an inline "
                        f".astype({dt}) operand with no "
                        "preferred_element_type: the contraction "
                        "accumulates (and rounds) in the narrow dtype "
                        "— add preferred_element_type=jnp.float32",
                        node=node)
                    break
                if dt in _AST_QUANT:
                    lint.add(
                        "RLT805",
                        f"{callee} consumes an inline .astype({dt}) "
                        "operand: quantized payloads need their f32 "
                        "dequantization scale applied before float "
                        "math",
                        node=node)
                    break
        out.extend(lint.findings)
    return out


def check_numerics_paths(paths: Sequence[str]) -> List[Finding]:
    """Run the static numerics pass over files/dirs (dirs expand
    recursively), mirroring concurrency.check_concurrency_paths."""
    from ray_lightning_tpu.analysis.linter import iter_python_files

    files = iter_python_files(paths)
    sources: List[Tuple[str, str]] = []
    common = ""
    if len(files) > 1:
        common = os.path.commonpath([os.path.abspath(f) for f in files])
    elif files:
        common = os.path.dirname(os.path.abspath(files[0]))
    for f in files:
        try:
            with open(f, "r", encoding="utf-8") as fh:
                source = fh.read()
        except OSError:
            continue
        rel = os.path.relpath(os.path.abspath(f), common) if common else f
        sources.append((rel, source))
    return check_numerics_sources(sources)
