"""shardcheck — pre-compile static analysis for sharding plans and
jitted training code.

Two zero-hardware engines sharing one Finding/rule vocabulary
(docs/STATIC_ANALYSIS.md):

  * plan checker (`check_plan`, plan_checker.py): abstract
    interpretation over MeshSpec/AbstractMesh + jax.eval_shape — proves
    a module's PartitionSpec overlay, optimizer-state dtypes, and step
    donation are well-formed before any pod time is spent;
  * code linter (`lint_paths`, linter.py): an AST pass over source files
    (never imported) flagging TPU/JAX antipatterns inside traced code —
    host transfers, Python RNG/wallclock/print, unhashable static args,
    unordered iteration — plus mesh-axis typos anywhere.

A third engine, tracecheck (`audit_step`, tracecheck.py), audits the
REAL jitted train step at the jaxpr level: the collective schedule with
a per-topology ICI cost model (costmodel.py), implicit-resharding
findings (RLT301), a liveness peak-HBM estimate vs the chip budget
(RLT302), and ring/pipeline ppermute schedule checks (RLT303).

CLI: `python -m ray_lightning_tpu lint [path|module]` and
`python -m ray_lightning_tpu trace <example|preset|module:factory>
[--topo v5p-64]` (analysis/cli.py).
"""
from ray_lightning_tpu.analysis.costmodel import (  # noqa: F401
    ICI_SPECS, CollectiveCost, Topology, collective_cost, parse_topology,
    topology_for_kind,
)
from ray_lightning_tpu.analysis.findings import (  # noqa: F401
    RULES, SEVERITY_RANK, Finding, Rule, max_severity, meets,
)
from ray_lightning_tpu.analysis.linter import (  # noqa: F401
    KNOWN_MESH_AXES, TRACED_STEP_HOOKS, lint_paths, lint_source,
)
from ray_lightning_tpu.analysis.plan_checker import (  # noqa: F401
    check_donation, check_opt_state_dtypes, check_param_specs, check_plan,
    spec_findings,
)
from ray_lightning_tpu.analysis.tracecheck import (  # noqa: F401
    CollectiveEvent, TraceReport, audit_step, check_permutation,
    trace_step,
)

__all__ = [
    "RULES", "SEVERITY_RANK", "Finding", "Rule", "max_severity", "meets",
    "KNOWN_MESH_AXES", "TRACED_STEP_HOOKS", "lint_paths", "lint_source",
    "check_donation", "check_opt_state_dtypes", "check_param_specs",
    "check_plan", "spec_findings",
    "ICI_SPECS", "CollectiveCost", "Topology", "collective_cost",
    "parse_topology", "topology_for_kind",
    "CollectiveEvent", "TraceReport", "audit_step", "check_permutation",
    "trace_step",
]
