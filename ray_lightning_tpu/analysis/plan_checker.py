"""shardcheck plan checker: abstract interpretation over
MeshSpec/AbstractMesh + jax.eval_shape that proves a module's sharding
plan is WELL-FORMED before any pod time is spent.

parallel/plan.py proves a plan *fits* (byte counts vs HBM); this engine
proves the plan *means what the author thinks*: every axis name exists,
every sharded dim divides, no axis is used twice, the optimizer state
doesn't silently widen, and donated buffers actually alias. All checks
run on `jax.eval_shape` abstractions over a `jax.sharding.AbstractMesh`
— zero devices of any kind, so an 8-chip dev box (or a CPU laptop) can
check a 4096-chip plan.

The composition code in parallel/strategy.py calls `spec_findings` on
every composed spec and raises on error-level findings, so the same
rules guard the live Trainer path, not only the offline checker.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from ray_lightning_tpu.analysis.findings import Finding

__all__ = [
    "spec_entries", "spec_findings", "check_param_specs",
    "check_opt_state_dtypes", "check_donation", "check_plan",
]


def spec_entries(spec) -> List[Tuple[int, Tuple[str, ...]]]:
    """Flatten a PartitionSpec-like into (dim_index, axis_names) pairs;
    unsharded dims yield empty tuples."""
    out: List[Tuple[int, Tuple[str, ...]]] = []
    for i, entry in enumerate(tuple(spec)):
        if entry is None:
            out.append((i, ()))
        elif isinstance(entry, (tuple, list)):
            out.append((i, tuple(entry)))
        else:
            out.append((i, (entry,)))
    return out


def spec_findings(
    spec,
    shape: Sequence[int],
    mesh_sizes: Mapping[str, int],
    *,
    path: str = "<leaf>",
) -> List[Finding]:
    """Validate ONE spec against one leaf shape and a mesh: unknown axes
    (RLT101), duplicate axes (RLT103), rank overflow (RLT104), uneven
    shard dims (RLT102)."""
    findings: List[Finding] = []
    entries = spec_entries(spec)
    seen: Dict[str, int] = {}
    for i, names in entries:
        for ax in names:
            if ax not in mesh_sizes:
                findings.append(Finding(
                    "RLT101",
                    f"spec for {path} names mesh axis {ax!r} which does "
                    f"not exist (mesh axes: {sorted(mesh_sizes)}); the "
                    "composition logic would silently drop it and "
                    "replicate the leaf", symbol=path))
            if ax in seen:
                findings.append(Finding(
                    "RLT103",
                    f"spec for {path} uses mesh axis {ax!r} on dims "
                    f"{seen[ax]} and {i}; an axis can shard at most one "
                    "dim", symbol=path))
            seen.setdefault(ax, i)
    rank = len(shape)
    if len(entries) > rank:
        findings.append(Finding(
            "RLT104",
            f"spec for {path} has {len(entries)} entries but the leaf "
            f"has rank {rank} (shape {tuple(shape)})", symbol=path))
        return findings
    for i, names in entries:
        divisor = math.prod(mesh_sizes.get(ax, 1) for ax in names)
        if divisor > 1 and shape[i] % divisor != 0:
            findings.append(Finding(
                "RLT102",
                f"dim {i} of {path} (size {shape[i]}, shape "
                f"{tuple(shape)}) cannot be partitioned evenly by "
                f"{'x'.join(names)} (={divisor})", symbol=path))
    return findings


def check_param_specs(
    specs: Optional[Mapping[str, Any]],
    named_params: Mapping[str, Any],
    mesh_sizes: Mapping[str, int],
) -> List[Finding]:
    """Validate a module's raw `param_specs()` overlay against the
    (abstract) parameter pytree: per-spec structural rules plus stale
    paths that match no parameter (RLT107)."""
    findings: List[Finding] = []
    for path, spec in (specs or {}).items():
        leaf = named_params.get(path)
        if leaf is None:
            findings.append(Finding(
                "RLT107",
                f"param_specs path {path!r} matches no parameter "
                "(renamed layer? the spec silently does nothing). "
                f"Nearest params: {_nearest(path, named_params)}",
                symbol=path))
            continue
        findings.extend(spec_findings(
            spec, getattr(leaf, "shape", ()), mesh_sizes, path=path))
    return findings


def _nearest(path: str, named_params: Mapping[str, Any], k: int = 3) -> str:
    tail = path.split("/")[-1]
    hits = [p for p in named_params if p.split("/")[-1] == tail][:k]
    return ", ".join(hits) if hits else "(none share the leaf name)"


def check_opt_state_dtypes(named_params: Mapping[str, Any],
                           named_opt: Mapping[str, Any]) -> List[Finding]:
    """Dtype-widening hazards: an optimizer-state leaf stored WIDER than
    the parameter it tracks (matched by the same longest-path-suffix +
    shape rule the strategies use for opt-state sharding inheritance)
    silently multiplies optimizer HBM — e.g. f32 Adam moments over bf16
    params are 2x the bytes the author likely budgeted."""
    findings: List[Finding] = []
    by_path = {p: leaf for p, leaf in named_params.items()}
    for opath, oleaf in named_opt.items():
        oshape = getattr(oleaf, "shape", None)
        odtype = getattr(oleaf, "dtype", None)
        if oshape is None or odtype is None:
            continue
        parts = opath.split("/")
        match = None
        for i in range(len(parts)):
            cand = "/".join(parts[i:])
            leaf = by_path.get(cand)
            if leaf is not None and getattr(leaf, "shape", ()) == oshape:
                match = (cand, leaf)
                break
        if match is None:
            continue
        ppath, pleaf = match
        # widths come from the SHARED table (costmodel.DTYPE_WIDTHS) so
        # this rule and numcheck's RLT804 judge "wider" identically —
        # tests/test_numcheck.py pins the two against each other
        from ray_lightning_tpu.analysis.costmodel import dtype_width

        p_size = dtype_width(getattr(pleaf, "dtype", None))
        o_size = dtype_width(odtype)
        if p_size and o_size and o_size > p_size:
            findings.append(Finding(
                "RLT105",
                f"optimizer state {opath} is {odtype} but its param "
                f"{ppath} is {pleaf.dtype}: the state is "
                f"{o_size / p_size:g}x wider than the weights it "
                "tracks (check mu_dtype/accumulator dtypes against the "
                "memory plan)", symbol=opath))
    return findings


def _leaf_key(leaf, sharding) -> Tuple:
    spec = getattr(sharding, "spec", sharding)
    spec_key = tuple(
        tuple(e) if isinstance(e, (tuple, list)) else e
        for e in tuple(spec)) if spec is not None else None
    return (tuple(getattr(leaf, "shape", ())),
            str(getattr(leaf, "dtype", "")), spec_key)


def check_donation(
    donated_named: Mapping[str, Tuple[Any, Any]],
    output_named: Mapping[str, Tuple[Any, Any]],
) -> List[Finding]:
    """Donation/aliasing audit: every donated input buffer must have an
    output buffer with identical (shape, dtype, sharding spec) to alias
    — otherwise XLA cannot reuse the donated memory and the step's true
    peak is a donated-buffer's-worth higher than planned.

    Both arguments map leaf paths to ``(abstract_leaf, sharding)``
    pairs (sharding may be None when unsharded); outputs are consumed
    at most once, mirroring XLA's aliasing rules. Paths are FULL
    pytree paths — nested dict/list opt-state leaves (custom optimizers
    that stash slots in containers) keep their complete
    ``opt_state/slots/1/...`` path in the finding, and a failed alias
    names the nearest same-shape output so the dtype/sharding drift
    that broke it is visible."""
    findings: List[Finding] = []
    pool: Dict[Tuple, int] = {}
    by_shape: Dict[Tuple, List[Tuple[str, Tuple]]] = {}
    for opath, (leaf, sh) in output_named.items():
        key = _leaf_key(leaf, sh)
        pool[key] = pool.get(key, 0) + 1
        by_shape.setdefault(key[0], []).append((opath, key))
    for path, (leaf, sh) in donated_named.items():
        key = _leaf_key(leaf, sh)
        if pool.get(key, 0) > 0:
            pool[key] -= 1
        else:
            near = by_shape.get(key[0], [])
            hint = (
                f" Nearest same-shape output: {near[0][0]} (dtype "
                f"{near[0][1][1]}, spec {near[0][1][2]})." if near
                else " No output has this shape at all.")
            findings.append(Finding(
                "RLT106",
                f"donated input {path} (shape {key[0]}, dtype {key[1]}, "
                f"spec {key[2]}) has no matching output buffer to alias "
                "— the donation is wasted and peak memory exceeds the "
                f"plan by this buffer.{hint}", symbol=path))
    return findings


def check_plan(
    module,
    strategy,
    n_devices: int,
    example_batch: Any,
) -> List[Finding]:
    """Full plan audit for ``module`` trained under ``strategy`` on
    ``n_devices`` — the well-formedness sibling of
    `parallel.plan.plan_train_memory` (same abstract build: AbstractMesh
    + eval_shape, zero devices; like the planner, it consumes the
    strategy instance — pass a fresh one).

    Returns findings from: the module's raw param_specs overlay
    (RLT101/102/103/104/107), the strategy-composed shardings (RLT102),
    optimizer-state dtypes (RLT105), and the canonical donated train
    step's in/out aliasing (RLT106).
    """
    import jax

    from ray_lightning_tpu.ops.dispatch import force_xla
    from ray_lightning_tpu.parallel.plan import _abstract, abstract_mesh
    from ray_lightning_tpu.utils.pytree import named_leaves

    spec = strategy.build_spec(n_devices).resolve(n_devices)
    mesh = abstract_mesh(spec)
    strategy.spec = spec
    strategy.mesh = mesh
    strategy.bind_module(module)
    module.setup()
    mesh_sizes = spec.sizes()

    findings: List[Finding] = []
    a_key = jax.eval_shape(lambda: jax.random.key(0))
    with force_xla():
        a_params = jax.eval_shape(
            module.init_params, a_key, _abstract(example_batch))
        named_params = dict(named_leaves(a_params))

        raw_specs = None
        if hasattr(module, "param_specs"):
            raw_specs = module.param_specs(a_params)
        findings.extend(
            check_param_specs(raw_specs, named_params, mesh_sizes))

        # compose through the strategy's real path; structural ERRORS the
        # raw check already reported would raise here — collect, don't die
        try:
            p_shardings = strategy.param_shardings(a_params)
            tx = module.configure_optimizers()
            a_opt = jax.eval_shape(tx.init, a_params)
            o_shardings = strategy.opt_state_shardings(a_opt, a_params)
        except ValueError:
            if not any(f.severity == "error" for f in findings):
                raise  # not a defect the raw pass explained — surface it
            return findings

    named_opt = dict(named_leaves(a_opt))
    findings.extend(check_opt_state_dtypes(named_params, named_opt))

    # composed shardings: the fsdp auto-placement only picks divisible
    # dims, but a module overlay can force an uneven split
    for (path, leaf), sh in zip(named_params.items(),
                                jax.tree.leaves(p_shardings)):
        findings.extend(spec_findings(
            sh.spec, leaf.shape, mesh_sizes, path=path))

    # donation audit on the canonical train step: (params, opt_state)
    # donated in, the optimizer update's ACTUAL outputs out — eval_shape
    # runs the real (grads -> tx.update -> apply_updates) tail so a
    # dtype/shape drift the optimizer introduces (the Trainer's donated
    # buffers then cannot alias) is caught, not assumed away
    donated = {f"params/{p}": (leaf, sh) for (p, leaf), sh in zip(
        named_params.items(), jax.tree.leaves(p_shardings))}
    donated.update({f"opt_state/{p}": (leaf, sh) for (p, leaf), sh in zip(
        named_opt.items(), jax.tree.leaves(o_shardings))})

    def _update_tail(params, opt_state):
        import optax

        # grads materialize at param shape/dtype during the step
        grads = jax.tree.map(lambda x: x, params)
        updates, new_opt = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt

    try:
        with force_xla():
            out_params, out_opt = jax.eval_shape(
                _update_tail, a_params, a_opt)
            out_p_sh = strategy.param_shardings(out_params)
            out_o_sh = strategy.opt_state_shardings(out_opt, out_params)
    except Exception:  # noqa: BLE001 — an optimizer eval_shape cannot
        # run abstractly: skip the donation audit rather than fail the
        # whole check (the other engines' findings still stand)
        return findings
    outputs = {f"params/{p}": (leaf, sh) for (p, leaf), sh in zip(
        named_leaves(out_params), jax.tree.leaves(out_p_sh))}
    outputs.update({f"opt_state/{p}": (leaf, sh) for (p, leaf), sh in zip(
        named_leaves(out_opt), jax.tree.leaves(out_o_sh))})
    findings.extend(check_donation(donated, outputs))
    return findings
