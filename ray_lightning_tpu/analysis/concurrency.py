"""threadcheck — host-side concurrency static analyzer (RLT7xx).

The analysis stack audits everything that happens *inside* jit
(shardcheck RLT1xx, tracecheck RLT3xx) but the host side around it is a
real threaded system: a prefetch producer, an async checkpoint
finalizer, heartbeat/report threads, accept loops, replica drivers.
threadcheck audits that layer the same way — whole-package AST pass,
same Finding vocabulary, same `# rlt: disable=` suppression syntax.

Thread model (what the analyzer actually proves):

* **thread-reachable code** — every ``threading.Thread(target=X)`` is
  resolved (bare name, ``self.method``, nested def, lambda) and the
  target's same-file call graph is closed over a fixpoint, exactly like
  the linter's traced-set propagation. Anything in that closure runs
  off the spawning thread.
* **guarded-by sets** — the stack of ``with <lock>:`` statements
  lexically enclosing a statement. A "lock" is an expression whose
  initializer is a known lock constructor (``threading.Lock/RLock/
  Condition/Semaphore``, ``analysis.lockwatch.san_lock``) or whose name
  says so (``*lock*``, ``*cond*``, ``*mutex*``, ``*cv*``).
  ``Condition(underlying)`` aliases to the underlying lock.
* **lock identity** — ``san_lock("name")`` locks are identified by
  their name package-wide; anonymous locks by ``file:Class.attr``.
  The RLT702 acquisition graph (edge A->B = B acquired while A held,
  through nested ``with`` chains *and* same-file calls) is merged
  across every file before cycle detection.

Rules:

* RLT701 unguarded-shared-mutation — ``self.X`` written in
  thread-reachable code and read/written outside it with no common
  lock. Sanctioned: attributes initialized to a synchronized carrier
  (``queue.Queue``, ``deque(maxlen=...)``, ``threading.Event``, locks),
  accesses in ``__init__`` or in the function that spawns the thread
  (they happen-before ``start()``).
* RLT702 lock-order-inversion — cycle in the package-wide acquisition
  graph.
* RLT703 thread-leak — started non-daemon thread with no ``join()``
  reachable for its binding.
* RLT704 signal-unsafe-handler — a ``signal.signal`` handler doing more
  than flag/``os.write``-class work (the bench.py/preempt.py flag-only
  discipline, enforced).
* RLT705 blocking-call-under-lock — sleep / thread join / subprocess /
  untimed queue op / file I/O while a lock is held. A lock whose every
  critical section is the same I/O (a dedicated append-serialization
  lock) is sanctioned: the hazard is a lock that also guards in-memory
  state.

Known limits (documented in docs/STATIC_ANALYSIS.md): resolution is
same-file (a thread target calling across modules is not followed);
``with``-based acquisition only (bare ``.acquire()`` is not tracked as
a guard); module-global races are out of scope for RLT701 (instance
attributes only). The runtime sanitizer (analysis/lockwatch.py) covers
the dynamic side of the same contract.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from ray_lightning_tpu.analysis.findings import Finding
from ray_lightning_tpu.analysis.linter import (
    _FileLint,
    _dotted,
    iter_python_files,
)

# ---- vocabulary ------------------------------------------------------------

#: constructors whose product is a lock (guard) — dotted suffixes
_LOCK_CTORS = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "san_lock",
}
#: reentrant lock constructors (self-edges in the order graph are legal)
_REENTRANT_CTORS = {"RLock", "san_rlock"}
#: constructors whose product is its own synchronization — an attribute
#: initialized to one of these is sanctioned for RLT701
_SYNC_CTORS = _LOCK_CTORS | {
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
    "Event", "Barrier", "local",
}
#: name heuristic for a with-expression that is a lock even when its
#: initializer is out of view (imported, built elsewhere)
_LOCKISH = ("lock", "mutex", "cond", "_cv")

#: receiver method calls that mutate the receiver (write, not read)
_MUTATORS = {
    "append", "appendleft", "add", "update", "pop", "popleft",
    "extend", "insert", "remove", "discard", "clear", "setdefault",
    "put", "put_nowait",
}

#: ops banned inside a signal handler (everything else — assignments,
#: os.write/os._exit, Event.set, arithmetic — is the sanctioned
#: flag-only discipline)
_HANDLER_BANNED_ATTRS = {
    "acquire", "flush", "sleep", "put", "get", "join", "start",
}
_HANDLER_BANNED_ROOTS = ("log", "logger", "logging", "subprocess")

#: blocking-call classes for RLT705
_IO_METHODS = {"write", "read", "readline", "readlines", "send", "recv",
               "sendall", "accept", "connect", "flush"}


def _self_chain(node: ast.AST) -> Optional[str]:
    """'a.b' for a self.a.b chain (root self stripped), else None."""
    d = _dotted(node)
    if d and d.startswith("self."):
        return d[len("self."):]
    return None


class _CFunc:
    """One function/method, with call edges for the reachability fixpoint."""

    __slots__ = ("node", "name", "qualname", "cls", "parent", "calls",
                 "thread", "spawner", "acquires", "blocking")

    def __init__(self, node, name: str, qualname: str, cls: Optional[str],
                 parent: Optional["_CFunc"]):
        self.node = node
        self.name = name
        self.qualname = qualname
        self.cls = cls
        self.parent = parent
        self.calls: Set[Tuple[str, str]] = set()   # ("self"|"name", name)
        self.thread = False      # in a thread target's call closure
        self.spawner = False     # constructs a Thread (pre-start publication)
        #: lock ids acquired in the body (directly; closed transitively
        #: by the file pass)
        self.acquires: Set[str] = set()
        #: transitive blocking calls: (klass, desc)
        self.blocking: Set[Tuple[str, str]] = set()


class _Access:
    __slots__ = ("cls", "chain", "write", "held", "func", "node")

    def __init__(self, cls, chain, write, held, func, node):
        self.cls = cls
        self.chain = chain
        self.write = write
        self.held: FrozenSet[str] = held
        self.func: _CFunc = func
        self.node = node


class _Spawn:
    __slots__ = ("node", "func", "daemon", "binding", "target_key")

    def __init__(self, node, func, daemon, binding, target_key):
        self.node = node
        self.func: _CFunc = func
        self.daemon = daemon            # True / False / None (absent)
        self.binding = binding          # "x" | "self.x" | None
        self.target_key = target_key    # ("self"|"name", name) | None


class _FileScan:
    """Everything one file contributes to the package-wide analysis."""

    def __init__(self, lint: _FileLint, relpath: str):
        self.lint = lint
        self.relpath = relpath
        self.funcs: List[_CFunc] = []
        self.by_name: Dict[str, List[_CFunc]] = {}
        self.by_method: Dict[Tuple[str, str], _CFunc] = {}
        self.accesses: List[_Access] = []
        self.spawns: List[_Spawn] = []
        self.joins: Set[str] = set()          # bindings with a .join() call
        self.daemon_sets: Set[str] = set()    # bindings with .daemon = True
        #: (handler_func_or_body, install_node)
        self.handlers: List[Tuple[object, ast.AST]] = []
        #: lock id -> constructor kind ("Lock"/"RLock"/...), when seen
        self.lock_kinds: Dict[str, str] = {}
        #: attr/name -> sanctioned-sync ctor name (RLT701 sanction)
        self.sync_attrs: Dict[Tuple[Optional[str], str], str] = {}
        #: attr/name -> san_lock("<name>") — the name IS the package-wide
        #: lock identity (shared with the runtime sanitizer)
        self.san_names: Dict[Tuple[Optional[str], str], str] = {}
        #: alias: (cls, chain) -> (cls, chain) — Condition(underlying)
        self.lock_alias: Dict[Tuple[Optional[str], str],
                              Tuple[Optional[str], str]] = {}
        #: (A, B, node) — B acquired (or blockingly entered) under A
        self.order_edges: List[Tuple[str, str, ast.AST]] = []
        #: candidate RLT705: (msg, node, lockid, klass)
        self.blocking_candidates: List[Tuple[str, ast.AST, str, str]] = []
        #: lock id -> list of per-section io flags (for the dedicated-
        #: I/O-lock sanction)
        self.lock_sections: Dict[str, List[bool]] = {}


# ---- pass 1: function table + initializer tables ---------------------------

class _Collector(ast.NodeVisitor):
    def __init__(self, scan: _FileScan):
        self.scan = scan
        self._cls: List[str] = []
        self._fn: List[_CFunc] = []

    def visit_ClassDef(self, node: ast.ClassDef):
        self._cls.append(node.name)
        self.generic_visit(node)
        self._cls.pop()

    def _handle_func(self, node):
        cls = self._cls[-1] if self._cls else None
        parent = self._fn[-1] if self._fn else None
        prefix = (parent.qualname + ".") if parent else (
            (cls + ".") if cls else "")
        fn = _CFunc(node, node.name, prefix + node.name, cls, parent)
        self.scan.funcs.append(fn)
        self.scan.by_name.setdefault(node.name, []).append(fn)
        if cls is not None and parent is None:
            self.scan.by_method[(cls, node.name)] = fn
        self._fn.append(fn)
        self.generic_visit(node)
        self._fn.pop()

    visit_FunctionDef = _handle_func
    visit_AsyncFunctionDef = _handle_func

    def visit_Call(self, node: ast.Call):
        if self._fn:
            d = _dotted(node.func)
            if d is not None:
                if d.startswith("self.") and "." not in d[5:]:
                    self._fn[-1].calls.add(("self", d[5:]))
                elif "." not in d:
                    self._fn[-1].calls.add(("name", d))
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        self._record_init(node.targets, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._record_init([node.target], node.value)
        self.generic_visit(node)

    def _record_init(self, targets, value):
        """Track `x = threading.Lock()` / `self._q = queue.Queue()` /
        `self._cond = threading.Condition(self._lock)` initializers."""
        if not isinstance(value, ast.Call):
            return
        ctor = _dotted(value.func)
        if ctor is None:
            return
        last = ctor.rsplit(".", 1)[-1]
        cls = self._cls[-1] if self._cls else None
        for t in targets:
            chain = _self_chain(t)
            key = (cls, chain) if chain else (
                (None, t.id) if isinstance(t, ast.Name) else None)
            if key is None or key[1] is None:
                continue
            if last in _SYNC_CTORS:
                self.scan.sync_attrs[key] = last
            if last == "deque" and any(k.arg == "maxlen"
                                       for k in value.keywords):
                self.scan.sync_attrs[key] = "deque(maxlen)"
            if last == "Condition" and value.args:
                under = value.args[0]
                uchain = _self_chain(under)
                ukey = ((cls, uchain) if uchain else
                        ((None, under.id)
                         if isinstance(under, ast.Name) else None))
                if ukey is not None:
                    self.scan.lock_alias[key] = ukey
            if last in ("san_lock", "san_rlock") and value.args \
                    and isinstance(value.args[0], ast.Constant) \
                    and isinstance(value.args[0].value, str):
                self.scan.san_names[key] = value.args[0].value
            if last in _LOCK_CTORS:
                lid = _lock_id_for_key(self.scan, key, value)
                self.scan.lock_kinds[lid] = last


def _lock_id_for_key(scan: _FileScan, key, ctor_call=None) -> str:
    """Stable package-wide identity for a lock binding. san_lock names
    ARE the identity (that is the point of naming them); anonymous locks
    get a file-qualified one."""
    if ctor_call is not None:
        d = _dotted(ctor_call.func) or ""
        if d.rsplit(".", 1)[-1] in ("san_lock", "san_rlock"):
            if ctor_call.args and isinstance(ctor_call.args[0], ast.Constant) \
                    and isinstance(ctor_call.args[0].value, str):
                return ctor_call.args[0].value
    if key in scan.san_names:
        return scan.san_names[key]
    cls, chain = key
    if cls:
        return f"{scan.relpath}:{cls}.{chain}"
    return f"{scan.relpath}:{chain}"


# ---- pass 2: per-function body scan with a held-lock stack -----------------

class _BodyScan:
    """Walks one function body tracking the with-lock stack; collects
    accesses, order edges, blocking calls, spawns, joins, handlers."""

    def __init__(self, scan: _FileScan, fn: _CFunc):
        self.scan = scan
        self.fn = fn
        self.held: List[str] = []
        #: io-flag stack parallel to `held` (does the current section of
        #: each held lock contain blocking I/O?)
        self._section_io: List[List[bool]] = []

    # -- lock resolution --

    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        d = _dotted(expr)
        if d is None:
            return None
        cls = self.fn.cls
        chain = _self_chain(expr)
        key = (cls, chain) if chain else (None, d)
        key = self.scan.lock_alias.get(key, key)
        known = (key in self.scan.sync_attrs
                 and self.scan.sync_attrs[key] in _LOCK_CTORS)
        last = key[1].rsplit(".", 1)[-1].lower()
        if not known and not any(t in last for t in _LOCKISH):
            return None
        return _lock_id_for_key(self.scan, key)

    # -- the walk --

    def run(self):
        for stmt in self.fn.node.body:
            self._stmt(stmt)

    def _stmt(self, node: ast.AST):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # scanned as its own _CFunc
        if isinstance(node, (ast.With, ast.AsyncWith)):
            self._with(node)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._assign(node)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)
            elif isinstance(child, ast.stmt):
                self._stmt(child)
            else:
                for sub in ast.iter_child_nodes(child):
                    if isinstance(sub, ast.expr):
                        self._expr(sub)
                    elif isinstance(sub, ast.stmt):
                        self._stmt(sub)

    def _with(self, node):
        new: List[str] = []
        for item in node.items:
            lid = self._lock_id(item.context_expr)
            if lid is not None:
                for h in self.held:
                    self.scan.order_edges.append((h, lid, item.context_expr))
                self.fn.acquires.add(lid)
                new.append(lid)
            else:
                self._expr(item.context_expr)
        for lid in new:
            self.held.append(lid)
            self._section_io.append([False])
        for stmt in node.body:
            self._stmt(stmt)
        for lid in reversed(new):
            self.held.pop()
            io_flag = self._section_io.pop()
            self.scan.lock_sections.setdefault(lid, []).append(io_flag[0])

    def _assign(self, node):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            self._store_target(t)
        if getattr(node, "value", None) is not None:
            self._expr(node.value)

    def _store_target(self, t: ast.AST):
        chain = _self_chain(t)
        if chain is not None and isinstance(t, ast.Attribute):
            self._access(chain, write=True, node=t)
            return
        if isinstance(t, ast.Subscript):
            chain = _self_chain(t.value)
            if chain is not None:
                self._access(chain, write=True, node=t)
            else:
                self._expr(t.value)
            self._expr(t.slice)
            return
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._store_target(el)
            return
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name):
            # t.daemon = True on a local thread binding
            if t.attr == "daemon":
                self.scan.daemon_sets.add(t.value.id)

    def _expr(self, node: ast.AST):
        if isinstance(node, ast.Call):
            self._call(node)
            return
        if isinstance(node, ast.Attribute):
            chain = _self_chain(node)
            if chain is not None:
                self._access(chain, write=False, node=node)
                return  # the whole chain was consumed
        if isinstance(node, ast.Lambda):
            self._expr(node.body)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)

    def _access(self, chain: str, write: bool, node: ast.AST):
        self.scan.accesses.append(_Access(
            self.fn.cls, chain, write, frozenset(self.held), self.fn, node))

    # -- calls: spawns, joins, blocking, handler installs, call edges --

    def _call(self, node: ast.Call):
        d = _dotted(node.func)
        last = d.rsplit(".", 1)[-1] if d else None

        if last == "Thread" and (d in ("Thread", "threading.Thread")
                                 or d.endswith(".Thread")):
            self._spawn(node)
        elif last == "signal" and d in ("signal.signal", "_signal.signal"):
            if len(node.args) >= 2:
                self.scan.handlers.append((node.args[1], node))
        elif d is not None:
            self._maybe_blocking(node, d, last)
            if isinstance(node.func, ast.Attribute):
                chain = _self_chain(node.func)
                if chain is not None and "." in chain:
                    # self.x.append(...) — mutation of self.x
                    base, meth = chain.rsplit(".", 1)
                    if meth in _MUTATORS:
                        self._access(base, write=True, node=node)
                    elif meth == "join":
                        self.scan.joins.add("self." + base)
                        self._access(base, write=False, node=node)
                    else:
                        self._access(base, write=False, node=node)
                elif (isinstance(node.func.value, ast.Name)
                      and node.func.attr == "join"):
                    self.scan.joins.add(node.func.value.id)
                else:
                    self._expr(node.func.value)

        for a in node.args:
            self._expr(a)
        for k in node.keywords:
            self._expr(k.value)

    def _spawn(self, node: ast.Call):
        self.fn.spawner = True
        daemon = None
        target_key = None
        for k in node.keywords:
            if k.arg == "daemon" and isinstance(k.value, ast.Constant):
                daemon = bool(k.value.value)
            if k.arg == "target":
                t = k.value
                td = _dotted(t)
                if td and td.startswith("self.") and "." not in td[5:]:
                    target_key = ("self", td[5:])
                elif td and "." not in td:
                    target_key = ("name", td)
        binding = self._binding_of(node)
        self.scan.spawns.append(
            _Spawn(node, self.fn, daemon, binding, target_key))

    def _binding_of(self, node: ast.Call) -> Optional[str]:
        """`x = Thread(...)` / `self.t = Thread(...)` binding, found by
        checking the parent Assign — the walk visits values through
        _assign so the parent targets are in scope via a second pass."""
        parent = getattr(node, "_rlt_parent_assign", None)
        if parent is None:
            return None
        for t in parent.targets if isinstance(parent, ast.Assign) else []:
            if isinstance(t, ast.Name):
                return t.id
            c = _self_chain(t)
            if c is not None:
                return "self." + c
        return None

    def _maybe_blocking(self, node: ast.Call, d: str, last: str):
        kwargs = {k.arg for k in node.keywords}
        klass = None
        if d in ("time.sleep", "sleep"):
            klass = "sleep"
        elif d.startswith("subprocess."):
            klass = "subprocess"
        elif d == "open":
            klass = "io"
        elif last in _IO_METHODS and not d.startswith("os."):
            klass = "io"
        elif last in ("get", "put") and "timeout" not in kwargs:
            base = d.rsplit(".", 1)[0].rsplit(".", 1)[-1].lower()
            if "q" == base or "queue" in base or base.endswith("q"):
                if not any(k.arg == "block" for k in node.keywords):
                    klass = "queue"
        elif last == "join" and isinstance(node.func, ast.Attribute):
            base = _dotted(node.func.value)
            if base and ("thread" in base.lower()
                         or base in self.scan.daemon_sets
                         or any(s.binding == base for s in self.scan.spawns)):
                klass = "join"
        if klass is None:
            return
        self.fn.blocking.add((klass, d))
        if self.held:
            if klass in ("io", "subprocess"):
                for flag in self._section_io:
                    flag[0] = True
            self.scan.blocking_candidates.append((
                f"`{d}(...)` ({klass}) runs while holding "
                f"{_short_lock(self.held[-1])}",
                node, self.held[-1], klass))


def _short_lock(lid: str) -> str:
    return f"lock `{lid}`" if ":" not in lid else f"lock `{lid.split(':', 1)[1]}`"


def _annotate_assign_parents(tree: ast.AST) -> None:
    """Stamp Call nodes with their enclosing Assign so _binding_of can
    recover `x = Thread(...)` bindings without a parent map."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            node.value._rlt_parent_assign = node  # type: ignore[attr-defined]


# ---- the package pass ------------------------------------------------------

def _scan_file(source: str, filename: str, relpath: str) -> Optional[_FileScan]:
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError:
        return None  # the shardcheck linter owns RLT001
    _annotate_assign_parents(tree)
    scan = _FileScan(_FileLint(source, filename), relpath)
    _Collector(scan).visit(tree)
    # module-level code is a scope too (handler installs, global lock
    # nests); scan it as a synthetic function outside by_name/by_method
    scan.funcs.append(_CFunc(tree, "<module>", "<module>", None, None))
    for fn in scan.funcs:
        _BodyScan(scan, fn).run()
    _close_file_fixpoints(scan)
    _per_file_rules(scan)
    return scan


def _close_file_fixpoints(scan: _FileScan) -> None:
    """Propagate thread-reachability, transitive lock acquisition, and
    transitive blocking over the same-file call graph."""
    # seed thread-reachable from spawn targets
    for s in scan.spawns:
        if s.target_key is None:
            continue
        kind, name = s.target_key
        targets: List[_CFunc] = []
        if kind == "self" and s.func.cls is not None:
            f = scan.by_method.get((s.func.cls, name))
            targets = [f] if f else scan.by_name.get(name, [])
        else:
            targets = scan.by_name.get(name, [])
        for f in targets:
            f.thread = True
    changed = True
    while changed:
        changed = False
        for fn in scan.funcs:
            callees: List[_CFunc] = []
            for kind, name in fn.calls:
                if kind == "self" and fn.cls is not None:
                    f = scan.by_method.get((fn.cls, name))
                    callees.extend([f] if f else [])
                else:
                    callees.extend(scan.by_name.get(name, []))
            for f in callees:
                if fn.thread and not f.thread:
                    f.thread = True
                    changed = True
                before = len(fn.acquires) + len(fn.blocking)
                fn.acquires |= f.acquires
                fn.blocking |= f.blocking
                if len(fn.acquires) + len(fn.blocking) != before:
                    changed = True
    # cross-function order edges + blocking-under-lock: a call made while
    # holding L reaches everything the callee acquires / blocks on
    _CrossCallScan(scan).run()


class _CrossCallScan:
    """Second body walk: now that per-function acquire/blocking summaries
    exist, attribute them to call sites made under a held lock."""

    def __init__(self, scan: _FileScan):
        self.scan = scan

    def run(self):
        for fn in self.scan.funcs:
            self._walk(fn, fn.node, [])

    def _resolve(self, fn: _CFunc, node: ast.Call) -> List[_CFunc]:
        d = _dotted(node.func)
        if d is None:
            return []
        if d.startswith("self.") and "." not in d[5:] and fn.cls:
            f = self.scan.by_method.get((fn.cls, d[5:]))
            return [f] if f else []
        if "." not in d:
            return self.scan.by_name.get(d, [])
        return []

    def _walk(self, fn: _CFunc, node: ast.AST, held: List[str]):
        if isinstance(node, (ast.With, ast.AsyncWith)) and node is not fn.node:
            body_scan = _BodyScan(self.scan, fn)
            new = [lid for item in node.items
                   if (lid := body_scan._lock_id(item.context_expr))]
            for stmt in node.body:
                self._walk(fn, stmt, held + new)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn.node:
            return
        if isinstance(node, ast.Call) and held:
            for callee in self._resolve(fn, node):
                for lid in callee.acquires:
                    for h in held:
                        if h != lid:
                            self.scan.order_edges.append((h, lid, node))
                for klass, desc in callee.blocking:
                    if klass in ("io", "subprocess"):
                        # mark every held lock's current section as io —
                        # approximated at section granularity elsewhere;
                        # here we only keep the finding candidate
                        pass
                    self.scan.blocking_candidates.append((
                        f"call to `{callee.qualname}()` blocks "
                        f"(`{desc}`, {klass}) while holding "
                        f"{_short_lock(held[-1])}",
                        node, held[-1], klass))
        for child in ast.iter_child_nodes(node):
            self._walk(fn, child, held)


def _per_file_rules(scan: _FileScan) -> None:
    _rule_701(scan)
    _rule_703(scan)
    _rule_704(scan)
    # RLT705 finalized at package level (needs the dedicated-I/O-lock
    # sanction computed across all sections of each lock)


def _rule_701(scan: _FileScan) -> None:
    groups: Dict[Tuple[Optional[str], str], List[_Access]] = {}
    for a in scan.accesses:
        groups.setdefault((a.cls, a.chain), []).append(a)
    for (cls, chain), accs in sorted(groups.items(),
                                     key=lambda kv: (kv[0][0] or "",
                                                     kv[0][1])):
        first = chain.split(".", 1)[0]
        if (cls, first) in scan.sync_attrs or (cls, chain) in scan.sync_attrs:
            continue  # synchronized carrier: its own synchronization
        thread_writes = [a for a in accs if a.write and a.func.thread
                         and a.func.name != "__init__"]
        outside = [a for a in accs
                   if not a.func.thread and a.func.name != "__init__"
                   and not a.func.spawner]
        if not thread_writes or not outside:
            continue
        for w in thread_writes:
            racy = [o for o in outside if not (w.held & o.held)]
            if racy:
                o = racy[0]
                scan.lint.add(
                    "RLT701",
                    f"`self.{chain}` is written in thread-reachable "
                    f"`{w.func.qualname}` and accessed in "
                    f"`{o.func.qualname}` (line {o.node.lineno}) with no "
                    f"common lock — guard both sides or hand it over via "
                    f"a queue.Queue/Event/deque(maxlen=...)",
                    node=w.node, symbol=f"{cls}.{chain}" if cls else chain)
                break  # one finding per attribute is enough signal


def _rule_703(scan: _FileScan) -> None:
    for s in scan.spawns:
        if s.daemon is True:
            continue
        b = s.binding
        if b is not None and (b in scan.joins or b in scan.daemon_sets):
            continue
        how = (f"bound to `{b}`" if b else "never bound to a name")
        scan.lint.add(
            "RLT703",
            f"non-daemon thread started in `{s.func.qualname}` ({how}) "
            f"has no join() on any path — process exit will block on it; "
            f"join it on the exit path or pass daemon=True",
            node=s.node)


def _rule_704(scan: _FileScan) -> None:
    for handler_expr, install in scan.handlers:
        bodies: List[ast.AST] = []
        label = "<handler>"
        seen: Set[int] = set()
        frontier: List[object] = [handler_expr]
        while frontier:
            h = frontier.pop()
            if isinstance(h, ast.Lambda):
                bodies.append(h.body)
                label = "<lambda>"
                continue
            fns: List[_CFunc] = []
            if isinstance(h, ast.Name):
                fns = scan.by_name.get(h.id, [])
            elif isinstance(h, ast.Attribute):
                c = _self_chain(h)
                if c and "." not in c:
                    fns = [f for f in [scan.by_method.get((cls, c))
                                       for cls in {f.cls for f in scan.funcs
                                                   if f.cls}]
                           if f]
            for f in fns:
                if id(f) in seen:
                    continue
                seen.add(id(f))
                label = f.qualname
                bodies.append(f.node)
                for kind, name in f.calls:
                    if kind == "name":
                        frontier.extend(scan.by_name.get(name, []))
                    elif f.cls:
                        m = scan.by_method.get((f.cls, name))
                        if m:
                            frontier.append(m)
        for body in bodies:
            bad = _handler_banned_op(body)
            if bad is not None:
                op, node = bad
                scan.lint.add(
                    "RLT704",
                    f"signal handler `{label}` does `{op}` — handlers "
                    f"must only flag and return (set an Event/flag, "
                    f"os.write, os._exit); do the real work at the next "
                    f"batch boundary (the bench.py/preempt.py "
                    f"discipline)",
                    node=node if hasattr(node, "lineno") else install)
                break


def _handler_banned_op(body: ast.AST):
    for node in ast.walk(body):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            return ("with-statement (lock?)", node)
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d is None:
            continue
        if d in ("print", "open", "input"):
            return (d, node)
        root = d.split(".", 1)[0]
        last = d.rsplit(".", 1)[-1]
        if root in _HANDLER_BANNED_ROOTS:
            return (d, node)
        if root == "os":
            continue  # os.write / os._exit / os.kill — sanctioned
        if last in _HANDLER_BANNED_ATTRS or d in ("time.sleep", "sleep"):
            return (d, node)
    return None


# ---- package-level finalization --------------------------------------------

def _finalize_705(scans: List[_FileScan]) -> None:
    sections: Dict[str, List[bool]] = {}
    for s in scans:
        for lid, flags in s.lock_sections.items():
            sections.setdefault(lid, []).extend(flags)
    io_dedicated = {lid for lid, flags in sections.items()
                    if flags and all(flags)}
    for s in scans:
        for msg, node, lid, klass in s.blocking_candidates:
            if klass in ("io", "subprocess") and lid in io_dedicated:
                continue  # the lock EXISTS to serialize this I/O
            s.lint.add(
                "RLT705",
                msg + " — copy state out under the lock and do the slow "
                "work outside",
                node=node)


def _finalize_702(scans: List[_FileScan]) -> None:
    graph: Dict[str, Dict[str, Tuple[str, int]]] = {}
    kinds: Dict[str, str] = {}
    for s in scans:
        kinds.update(s.lock_kinds)
        for a, b, node in s.order_edges:
            if a == b:
                continue  # self-edge: runtime lockwatch's department
            graph.setdefault(a, {}).setdefault(
                b, (s.relpath, getattr(node, "lineno", 0)))
    reported: Set[FrozenSet[str]] = set()
    for start in sorted(graph):
        path: List[str] = []
        on_path: Set[str] = set()

        def dfs(n: str) -> Optional[List[str]]:
            path.append(n)
            on_path.add(n)
            for m in sorted(graph.get(n, ())):
                if m == start and len(path) > 1:
                    return path[:]
                if m not in on_path and m in graph:
                    cyc = dfs(m)
                    if cyc:
                        return cyc
            path.pop()
            on_path.discard(n)
            return None

        cycle = dfs(start)
        if not cycle:
            continue
        key = frozenset(cycle)
        if key in reported:
            continue
        reported.add(key)
        hops = []
        for i, n in enumerate(cycle):
            nxt = cycle[(i + 1) % len(cycle)]
            f, ln = graph[n][nxt]
            hops.append(f"`{n}` -> `{nxt}` ({f}:{ln})")
        anchor = graph[cycle[0]][cycle[1 % len(cycle)]]
        scan0 = next((s for s in scans if s.relpath == anchor[0]), scans[0])
        scan0.lint.findings.append(Finding(
            rule="RLT702",
            message=("lock-order cycle: " + ", ".join(hops)
                     + " — two threads taking these in opposite orders "
                       "deadlock; impose one global acquisition order"),
            file=scan0.lint.filename, line=anchor[1]))


# ---- public API ------------------------------------------------------------

def check_concurrency_sources(
        sources: Sequence[Tuple[str, str]]) -> List[Finding]:
    """Run threadcheck over (filename, source) pairs as one package."""
    scans: List[_FileScan] = []
    for filename, source in sources:
        rel = os.path.basename(filename)
        s = _scan_file(source, filename, rel)
        if s is not None:
            scans.append(s)
    if not scans:
        return []
    _finalize_705(scans)
    _finalize_702(scans)
    out: List[Finding] = []
    for s in scans:
        out.extend(s.lint.findings)
    return out


def check_concurrency_paths(paths: Sequence[str]) -> List[Finding]:
    """Run threadcheck over files/dirs (dirs expand recursively). Files
    that do not parse are skipped — the shardcheck linter owns RLT001."""
    files = iter_python_files(paths)
    common = os.path.commonpath([os.path.abspath(f) for f in files]) \
        if len(files) > 1 else os.path.dirname(os.path.abspath(files[0])) \
        if files else ""
    scans: List[_FileScan] = []
    for f in files:
        try:
            with open(f, "r", encoding="utf-8") as fh:
                source = fh.read()
        except OSError:
            continue
        rel = os.path.relpath(os.path.abspath(f), common) if common else f
        s = _scan_file(source, f, rel)
        if s is not None:
            scans.append(s)
    if not scans:
        return []
    _finalize_705(scans)
    _finalize_702(scans)
    out: List[Finding] = []
    for s in scans:
        out.extend(s.lint.findings)
    return out


def summarize(findings: Sequence[Finding]) -> dict:
    """Counts-by-rule block for bench JSON lines (backend-down safe —
    pure host-side AST work)."""
    by_rule: Dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return {"total": len(findings), "by_rule": dict(sorted(by_rule.items()))}
