"""Sharded checkpoint I/O (orbax-backed).

Design requirement from SURVEY §3.4/§5.4: the reference round-trips full
state dicts through the driver (ray_ddp.py:186-193) and even ships whole
checkpoint dicts through a queue actor for Tune (tune.py:128-142) — a
scaling hazard it explicitly must NOT copy for 8B-param models. Here:

  * workers write *sharded* checkpoints in place (each host saves only its
    addressable shards — orbax handles the multi-host protocol);
  * only paths + small metadata travel between processes;
  * a small-model convenience path (`load_checkpoint`) gathers to host for
    the reference's `load_from_checkpoint` UX.

Layout of a checkpoint directory:
    <path>/state/     orbax pytree ({"params", "opt_state", "step"} or subset)
    <path>/meta.json  {epoch, global_step, module_class, hparams_pickle_hex}
"""
from __future__ import annotations

import json
import os
import pickle
from typing import Any, Dict, List, Optional, Tuple

import jax
import orbax.checkpoint as ocp

_STATE_DIR = "state"
_META_FILE = "meta.json"

# meta.json writes deferred until their async state write finalizes —
# meta.json presence is the "checkpoint is complete" marker, so it must
# never exist over a still-streaming (or failed) state dir.
_PENDING_META: List[Tuple[str, Dict[str, Any]]] = []

# Singleton: StandardCheckpointer is an AsyncCheckpointer — in-flight
# background writes must not be garbage-collected with a per-call
# instance, and wait_for_checkpoints() needs a handle to join them.
_CKPT: Optional[ocp.StandardCheckpointer] = None


def _checkpointer() -> ocp.StandardCheckpointer:
    global _CKPT
    if _CKPT is None:
        _CKPT = ocp.StandardCheckpointer()
    return _CKPT


def save_checkpoint(
    path: str,
    state: Dict[str, Any],
    meta: Optional[Dict[str, Any]] = None,
    block: bool = True,
) -> str:
    """Write `state` (pytree of possibly-sharded jax.Arrays) + metadata.

    Multi-host safe: every process must call this collectively; orbax
    writes each host's addressable shards.

    ``block=False`` returns as soon as the device->host copy is done and
    streams the disk write in the background (training continues during
    I/O — the big-model checkpoint stall killer); join with
    `wait_for_checkpoints()` before reading the files or exiting.
    """
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    meta = dict(meta or {})
    hparams = meta.pop("hparams", None)
    if hparams is not None:
        meta["hparams_pickle_hex"] = pickle.dumps(hparams).hex()
    ck = _checkpointer()
    ck.save(os.path.join(path, _STATE_DIR), state, force=True)
    if block:
        ck.wait_until_finished()
        # the join above finalized EVERY in-flight write, including earlier
        # async ones — flush their deferred metas too, then write ours
        _flush_pending_meta()
        _write_meta(path, meta)
    else:
        # meta.json is the completeness marker — defer it until
        # wait_for_checkpoints() confirms the state write finalized.
        _PENDING_META.append((path, meta))
    return path


def _write_meta(path: str, meta: Dict[str, Any]) -> None:
    if jax.process_index() == 0:
        with open(os.path.join(path, _META_FILE), "w") as f:
            json.dump(meta, f)


def _flush_pending_meta() -> None:
    global _PENDING_META
    pending, _PENDING_META = _PENDING_META, []
    for path, meta in pending:
        _write_meta(path, meta)


def discard_pending_meta(path: str) -> bool:
    """Forget the deferred meta for `path` (its checkpoint dir is being
    deleted). Returns True if an entry existed — i.e. the state write may
    still be streaming into that dir, so callers should join in-flight
    writes before removing it."""
    global _PENDING_META
    p = os.path.abspath(path)
    had = any(pp == p for pp, _ in _PENDING_META)
    if had:
        _PENDING_META = [(pp, m) for pp, m in _PENDING_META if pp != p]
    return had


def wait_for_checkpoints() -> None:
    """Join all in-flight async checkpoint writes (no-op when none), then
    finalize their meta.json markers. If any write failed, NO deferred meta
    is written (conservative: an un-finalized dir reads as no checkpoint)
    and the error propagates to the caller."""
    global _PENDING_META
    try:
        if _CKPT is not None:
            _CKPT.wait_until_finished()
    except Exception:
        _PENDING_META = []
        raise
    _flush_pending_meta()


def load_checkpoint(path: str) -> Dict[str, Any]:
    """Small-model convenience: restore everything to host-local arrays.

    Returns the state dict merged with parsed metadata (incl. "hparams").
    """
    path = os.path.abspath(path)
    state = _checkpointer().restore(os.path.join(path, _STATE_DIR))
    out = dict(state)
    out.update(_read_meta(path))
    return out


def restore_checkpoint(path: str, target: Any) -> Any:
    """Sharding-preserving restore: `target` is a pytree of jax.Arrays or
    ShapeDtypeStructs (with `.sharding` set) giving the layout to restore
    into — each host reads only its shards. Used for resume at scale."""
    path = os.path.abspath(path)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=getattr(x, "sharding", None)),
        target,
    )
    return _checkpointer().restore(os.path.join(path, _STATE_DIR), abstract)


def read_meta(path: str) -> Dict[str, Any]:
    return _read_meta(os.path.abspath(path))


def _read_meta(path: str) -> Dict[str, Any]:
    meta_path = os.path.join(path, _META_FILE)
    if not os.path.exists(meta_path):
        return {}
    with open(meta_path) as f:
        meta = json.load(f)
    hex_ = meta.pop("hparams_pickle_hex", None)
    if hex_:
        meta["hparams"] = pickle.loads(bytes.fromhex(hex_))
    return meta
