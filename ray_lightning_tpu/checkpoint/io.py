"""Sharded checkpoint I/O (orbax-backed).

Design requirement from SURVEY §3.4/§5.4: the reference round-trips full
state dicts through the driver (ray_ddp.py:186-193) and even ships whole
checkpoint dicts through a queue actor for Tune (tune.py:128-142) — a
scaling hazard it explicitly must NOT copy for 8B-param models. Here:

  * workers write *sharded* checkpoints in place (each host saves only its
    addressable shards — orbax handles the multi-host protocol);
  * only paths + small metadata travel between processes;
  * a small-model convenience path (`load_checkpoint`) gathers to host for
    the reference's `load_from_checkpoint` UX.

Layout of a checkpoint directory:
    <path>/state/     orbax pytree ({"params", "opt_state", "step"} or subset)
    <path>/meta.json  {epoch, global_step, module_class, hparams_pickle_hex,
                       ckpt_digest, ckpt_files, ckpt_digest_mode}

Atomicity & verifiability (the resilience subsystem's resume source of
truth, docs/RESILIENCE.md): orbax itself writes the state tree into a
temp dir and renames on finalize, so the state dir is never observable
half-written; meta.json — the "checkpoint is complete" marker — is
written AFTER the state finalizes, to a temp file + os.replace (atomic
on POSIX), and records a content digest of the finalized state files.
``latest_checkpoint(dir)`` walks candidates newest-first and returns the
first that VERIFIES — torn dirs (no meta), partial dirs (file-set
mismatch) and corrupt dirs (digest mismatch) are skipped, so a
supervisor resume can never load the checkpoint the crash tore.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from ray_lightning_tpu.analysis.lockwatch import san_lock
from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)

_STATE_DIR = "state"
_META_FILE = "meta.json"

# meta.json writes deferred until their async state write finalizes —
# meta.json presence is the "checkpoint is complete" marker, so it must
# never exist over a still-streaming (or failed) state dir. Entries are
# published by the background finalizer thread the moment their state
# write commits (or by wait_for_checkpoints / the next blocking save,
# whichever runs first); _META_LOCK guards the list.
_PENDING_META: List[Tuple[str, Dict[str, Any]]] = []
_META_LOCK = san_lock("checkpoint.io.meta")

#: paths whose meta/digest the finalizer thread is writing RIGHT NOW —
#: deletion (checkpoint pruning) must not rmtree a dir mid-digest-walk.
#: Guarded by _META_LOCK via the condition below.
_FINALIZING: set = set()
_FIN_CV = threading.Condition(_META_LOCK)

#: async-write failures recorded by the finalizer thread; surfaced (and
#: cleared) by the next wait_for_checkpoints()/save_checkpoint().
_ASYNC_ERRORS: List[BaseException] = []

#: finalizer thread: one daemon per process draining a queue of paths
#: whose meta/digest should be published as soon as the orbax commit
#: lands — a crash BETWEEN checkpoint cadences must not cost a fully
#: written checkpoint its completeness marker.
_FIN_QUEUE: "queue.Queue[str]" = queue.Queue()
_FIN_THREAD: Optional[threading.Thread] = None

#: overlap accounting (save stalls are the number the async path exists
#: to shrink); read via io_stats(), surfaced in callback_metrics.
_STATS = {"async_saves": 0, "blocking_saves": 0,
          "stall_s": 0.0, "last_stall_s": 0.0}

# Singleton: StandardCheckpointer is an AsyncCheckpointer — in-flight
# background writes must not be garbage-collected with a per-call
# instance, and wait_for_checkpoints() needs a handle to join them.
_CKPT: Optional[ocp.StandardCheckpointer] = None

#: serializes every save()/wait_until_finished() on the checkpointer:
#: orbax's wait does `thread.join(); self._thread = None`, so a
#: finalizer-thread wait racing a new main-thread save could null out
#: the NEW commit thread's handle — a later wait would then return
#: early and meta could be published over a still-streaming write.
#: Holding the lock through a wait costs nothing extra: a concurrent
#: save would have waited for the in-flight write inside orbax anyway.
_CK_LOCK = san_lock("checkpoint.io.ck", reentrant=True)


def _checkpointer() -> ocp.StandardCheckpointer:
    global _CKPT
    if _CKPT is None:
        _CKPT = ocp.StandardCheckpointer()
    return _CKPT


def io_stats() -> Dict[str, float]:
    """Checkpoint-overlap counters: cumulative seconds the TRAINING
    thread spent blocked waiting for earlier checkpoint writes
    (``ckpt_stall_s``) and the save counts. The async path's win is this
    number staying ~0 while checkpoints still land."""
    return {
        "ckpt_async_saves": float(_STATS["async_saves"]),
        "ckpt_blocking_saves": float(_STATS["blocking_saves"]),
        "ckpt_stall_s": _STATS["stall_s"],
        "ckpt_last_stall_s": _STATS["last_stall_s"],
    }


def device_snapshot(tree: Any) -> Any:
    """Fresh runtime-owned device buffers for `tree` via the no-donation
    jitted identity: the output CANNOT alias the input, so the snapshot
    survives the trainer donating the live state into the next step
    while the background write streams from it. (The same mechanism
    `restore_checkpoint` uses in the other direction — donating
    TensorStore-owned buffers corrupted resumed weights.)"""
    return jax.jit(lambda t: t)(tree)


def _timed_drain(ck) -> None:
    """Join any in-flight write on the calling (training) thread and
    account the wait as checkpoint stall."""
    t0 = time.perf_counter()
    try:
        with _CK_LOCK:
            ck.wait_until_finished()
    except Exception as exc:  # noqa: BLE001 — recorded, surfaced below
        with _META_LOCK:
            _ASYNC_ERRORS.append(exc)
    stall = time.perf_counter() - t0
    _STATS["stall_s"] += stall
    _STATS["last_stall_s"] = stall


def _raise_recorded_errors() -> None:
    """Surface (once) any failure the background machinery recorded; a
    failed write conservatively drops ALL deferred metas — an
    un-finalized dir reads as no checkpoint."""
    with _META_LOCK:
        if not _ASYNC_ERRORS:
            return
        errors, _ASYNC_ERRORS[:] = list(_ASYNC_ERRORS), []
        _PENDING_META.clear()
    raise errors[0]


def save_checkpoint(
    path: str,
    state: Dict[str, Any],
    meta: Optional[Dict[str, Any]] = None,
    block: bool = True,
) -> str:
    """Write `state` (pytree of possibly-sharded jax.Arrays) + metadata.

    Multi-host safe: every process must call this collectively; orbax
    writes each host's addressable shards.

    ``block=False`` is a real background commit: the state is snapshotted
    on device via the no-donation identity (so the trainer may donate the
    live state into the very next step), the serialize streams in the
    background, and a finalizer thread publishes meta.json + content
    digest the moment the state write commits — atomically, so a crash at
    any point leaves either a complete, verifiable checkpoint or a torn
    dir that `latest_checkpoint` skips. Join with `wait_for_checkpoints()`
    before reading the files or exiting; time spent here waiting for a
    previous in-flight write is accounted as ``ckpt_stall_s``
    (`io_stats`).
    """
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    meta = dict(meta or {})
    hparams = meta.pop("hparams", None)
    if hparams is not None:
        meta["hparams_pickle_hex"] = pickle.dumps(hparams).hex()
    ck = _checkpointer()
    # drain any previous in-flight write OURSELVES (orbax would anyway,
    # inside save) so the wait is measured as checkpoint stall — the
    # number the async pipeline exists to shrink — and so a recorded
    # background failure surfaces here rather than half-way into orbax.
    _timed_drain(ck)
    _raise_recorded_errors()
    if not block:
        state = device_snapshot(state)
    with _CK_LOCK:
        ck.save(os.path.join(path, _STATE_DIR), state, force=True)
    if block:
        _STATS["blocking_saves"] += 1
        with _CK_LOCK:
            ck.wait_until_finished()
        # the join above finalized EVERY in-flight write, including earlier
        # async ones — flush their deferred metas too, then write ours
        _flush_pending_meta()
        _write_meta(path, meta)
    else:
        _STATS["async_saves"] += 1
        # meta.json is the completeness marker — deferred until the state
        # write finalizes; the finalizer thread publishes it eagerly.
        with _META_LOCK:
            _PENDING_META.append((path, meta))
        _ensure_finalizer()
        _FIN_QUEUE.put(path)
    return path


#: digest policy (env RLT_CKPT_DIGEST): "full" hashes file contents —
#: the default, and what corrupt-checkpoint detection needs; "size"
#: hashes only (relpath, size) — cheap at 8B scale, still catches torn
#: and truncated files; "off" records no digest.
_DIGEST_MODE_ENV = "RLT_CKPT_DIGEST"


def _digest_mode() -> str:
    mode = os.environ.get(_DIGEST_MODE_ENV, "full")
    return mode if mode in ("full", "size", "off") else "full"


def compute_state_digest(path: str, mode: str = "full") -> Tuple[str, int]:
    """(sha256 hexdigest, file count) over the finalized state dir —
    deterministic: files visited in sorted relpath order."""
    state_dir = os.path.join(os.path.abspath(path), _STATE_DIR)
    h = hashlib.sha256()
    count = 0
    entries = []
    for root, dirs, files in os.walk(state_dir):
        dirs.sort()
        for f in sorted(files):
            full = os.path.join(root, f)
            entries.append((os.path.relpath(full, state_dir), full))
    for rel, full in sorted(entries):
        size = os.path.getsize(full)
        h.update(f"{rel}\x00{size}\x00".encode())
        count += 1
        if mode == "full":
            with open(full, "rb") as fh:
                for chunk in iter(lambda: fh.read(1 << 20), b""):
                    h.update(chunk)
    return h.hexdigest(), count


def _write_meta(path: str, meta: Dict[str, Any]) -> None:
    if jax.process_index() != 0:
        return
    meta = dict(meta)
    mode = _digest_mode()
    meta["ckpt_digest_mode"] = mode
    if mode != "off":
        try:
            digest, count = compute_state_digest(path, mode)
            meta["ckpt_digest"] = digest
            meta["ckpt_files"] = count
        except OSError:
            # a digest failure must not lose the checkpoint itself; the
            # meta lands digest-less and verification degrades to
            # presence checks
            log.exception("could not digest checkpoint %s", path)
    meta_path = os.path.join(path, _META_FILE)
    tmp = meta_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
    # atomic publish: a crash mid-write leaves only the tmp file and the
    # checkpoint reads as incomplete (no meta.json), never as torn JSON
    os.replace(tmp, meta_path)


def sharding_provenance(mesh, state: Any) -> Dict[str, Any]:
    """The topology-provenance stamps for a checkpoint's meta
    (docs/ELASTIC.md "resharding restore"): which mesh wrote it and how
    each leaf was laid out, so a cross-topology restore
    (`elastic.reshard.reshard_restore`) can VALIDATE the move instead
    of trusting the caller.

      mesh_spec     {axis: size} of the writing mesh (all axes)
      topology      n_devices / process_count / platform at write time
      param_specs   {tree path: per-dim spec} for every leaf of
                    ``state["params"]`` whose sharding is known — the
                    JSON form of its PartitionSpec (None = unsharded
                    dim, a list = the axis names on that dim)

    Opt-state specs are not recorded: they inherit their param's layout
    by construction (Strategy.opt_state_shardings), so the param table
    is the whole story. Tolerant of missing pieces (a host-numpy tree
    has no shardings) — absent stamps simply mean legacy semantics."""
    out: Dict[str, Any] = {}
    if mesh is None:
        return out
    try:
        shape = dict(mesh.shape)
    except Exception:  # noqa: BLE001 — provenance is best-effort
        return out
    out["mesh_spec"] = {str(k): int(v) for k, v in shape.items()}
    try:
        platform = mesh.devices.flat[0].platform
    except Exception:  # noqa: BLE001 — AbstractMesh has no devices
        platform = None
    n_devices = 1
    for v in out["mesh_spec"].values():
        n_devices *= v
    out["topology"] = {
        "n_devices": n_devices,
        "process_count": jax.process_count(),
        "platform": platform,
    }
    params = (state or {}).get("params") if isinstance(state, dict) \
        else None
    if params is not None:
        from ray_lightning_tpu.utils.pytree import named_leaves

        specs: Dict[str, Any] = {}
        try:
            for path, leaf in named_leaves(params):
                spec = getattr(getattr(leaf, "sharding", None), "spec",
                               None)
                if spec is None:
                    continue
                specs[path] = [
                    None if d is None
                    else list(d) if isinstance(d, (tuple, list))
                    else str(d)
                    for d in tuple(spec)
                ]
        except Exception:  # noqa: BLE001 — best-effort
            specs = {}
        if specs:
            out["param_specs"] = specs
    return out


def verify_checkpoint(path: str) -> Tuple[bool, str]:
    """Is this directory a complete, uncorrupted checkpoint?
    Returns (ok, reason) — reason names the first failed check."""
    path = os.path.abspath(path)
    state_dir = os.path.join(path, _STATE_DIR)
    if not os.path.isdir(state_dir):
        return False, "no state dir (write never started or was removed)"
    meta_path = os.path.join(path, _META_FILE)
    if not os.path.exists(meta_path):
        return False, "no meta.json (write never finalized — torn)"
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (OSError, ValueError) as exc:
        return False, f"unreadable meta.json ({exc})"
    recorded = meta.get("ckpt_digest")
    mode = meta.get("ckpt_digest_mode", "off")
    if recorded and mode in ("full", "size"):
        try:
            digest, count = compute_state_digest(path, mode)
        except OSError as exc:
            return False, f"state unreadable ({exc})"
        if count != meta.get("ckpt_files", count):
            return False, (f"partial state: {count} files on disk vs "
                           f"{meta.get('ckpt_files')} recorded")
        if digest != recorded:
            return False, "digest mismatch (corrupt or tampered state)"
    ok, reason = _verify_provenance(meta)
    if not ok:
        return False, reason
    return True, "ok"


def _verify_provenance(meta: Dict[str, Any]) -> Tuple[bool, str]:
    """Internal consistency of the sharding-provenance stamps (when
    present — legacy checkpoints without them verify fine): the mesh
    axis product must equal the recorded device count, and every axis a
    param spec names must exist in the writing mesh. A checkpoint whose
    own provenance is self-contradictory would make a resharding
    restore validate against fiction."""
    mesh_spec = meta.get("mesh_spec")
    if mesh_spec is None:
        return True, "ok"
    if not isinstance(mesh_spec, dict) or not all(
            isinstance(v, int) and v >= 1 for v in mesh_spec.values()):
        return False, "malformed mesh_spec provenance (non-integer axes)"
    n = 1
    for v in mesh_spec.values():
        n *= v
    topo = meta.get("topology") or {}
    rec_n = topo.get("n_devices")
    if rec_n is not None and int(rec_n) != n:
        return False, (f"provenance mismatch: mesh_spec covers {n} "
                       f"devices but topology records {rec_n}")
    for p, spec in (meta.get("param_specs") or {}).items():
        for dim in spec or ():
            names = dim if isinstance(dim, list) else \
                [dim] if dim is not None else []
            for name in names:
                if name not in mesh_spec:
                    return False, (
                        f"provenance mismatch: param_specs[{p!r}] names "
                        f"mesh axis {name!r} absent from mesh_spec "
                        f"{sorted(mesh_spec)}")
    return True, "ok"


def latest_checkpoint(directory: str, *, good_only: bool = False,
                      max_step: Optional[int] = None) -> Optional[str]:
    """Newest VALID checkpoint under ``directory`` (the dir itself is
    also considered, so both a checkpoint path and a dir of checkpoints
    work). Candidates ordered by recorded global_step (mtime breaks
    ties), newest first; torn/partial/corrupt candidates are skipped
    with a logged reason. None when nothing valid exists.

    ``good_only=True`` additionally requires the trainguard blessing
    (``meta["blessed"]`` — checkpoints saved inside an anomaly window
    are stamped False and skipped; checkpoints without the field, e.g.
    pre-guard ones, count as blessed). ``max_step`` caps the candidate's
    recorded global_step — a corruption rollback passes the last
    known-good step so a blessed-but-possibly-poisoned newer checkpoint
    (an SDC bit-flip is silent until the probe catches it) is never the
    resume source."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    candidates = []
    names = [directory] + [
        os.path.join(directory, d) for d in os.listdir(directory)
        if os.path.isdir(os.path.join(directory, d))
    ]
    for cand in names:
        if not os.path.isdir(os.path.join(cand, _STATE_DIR)):
            continue
        step = -1
        blessed = None
        meta_path = os.path.join(cand, _META_FILE)
        try:
            with open(meta_path) as f:
                meta = json.load(f)
            step = int(meta.get("global_step", -1))
            blessed = meta.get("blessed")
        except (OSError, ValueError, TypeError):
            pass  # still a candidate; verify_checkpoint rejects it below
        if good_only and blessed is False:
            log.info("skipping unblessed checkpoint %s (saved inside a "
                     "trainguard anomaly window)", cand)
            continue
        if max_step is not None and step > max_step:
            log.info("skipping checkpoint %s: step %d is past the "
                     "rollback horizon %d", cand, step, max_step)
            continue
        try:
            mtime = os.path.getmtime(cand)
        except OSError:
            continue
        candidates.append((step, mtime, cand))
    for _, _, cand in sorted(candidates, reverse=True):
        ok, reason = verify_checkpoint(cand)
        if ok:
            return cand
        log.warning("skipping invalid checkpoint %s: %s", cand, reason)
    return None


def _ensure_finalizer() -> None:
    """Start the per-process finalizer thread (idempotent)."""
    global _FIN_THREAD
    if _FIN_THREAD is not None and _FIN_THREAD.is_alive():
        return
    _FIN_THREAD = threading.Thread(
        target=_finalizer_loop, name="rlt-ckpt-finalize", daemon=True)
    _FIN_THREAD.start()


def _finalizer_loop() -> None:
    """Publish each async save's meta/digest as soon as its state write
    commits. Entries are processed one at a time: when we dequeue a path
    its orbax save has already STARTED (save_checkpoint enqueues after
    ck.save returned), so wait_until_finished() returning means THAT
    write committed — publishing only this entry's meta can never mark a
    later, still-streaming checkpoint complete."""
    while True:
        path = _FIN_QUEUE.get()
        try:
            try:
                with _CK_LOCK:
                    _checkpointer().wait_until_finished()
            except Exception as exc:  # noqa: BLE001 — surfaced on next join
                with _META_LOCK:
                    _ASYNC_ERRORS.append(exc)
                    # the torn write must never gain a completeness marker
                    _discard_locked(path)
                continue
            # take-and-mark atomically: once marked, a concurrent
            # discard_pending_meta (checkpoint pruning about to rmtree
            # this dir) BLOCKS until the meta/digest write is off the
            # directory; once discarded, we skip the write entirely.
            with _FIN_CV:
                meta = _take_pending_locked(path)
                if meta is not None:
                    _FINALIZING.add(path)
            if meta is not None:
                try:
                    _write_meta(path, meta)
                finally:
                    with _FIN_CV:
                        _FINALIZING.discard(path)
                        _FIN_CV.notify_all()
        except Exception as exc:  # noqa: BLE001 — a meta/digest failure
            # is an async error like any other; never kill the thread
            with _META_LOCK:
                _ASYNC_ERRORS.append(exc)
        finally:
            _FIN_QUEUE.task_done()


def _take_pending_locked(path: str) -> Optional[Dict[str, Any]]:
    for i, (pp, meta) in enumerate(_PENDING_META):
        if pp == path:
            del _PENDING_META[i]
            return meta
    return None


def _flush_pending_meta() -> None:
    while True:
        with _META_LOCK:
            if not _PENDING_META:
                return
            path, meta = _PENDING_META.pop(0)
        _write_meta(path, meta)


def _discard_locked(path: str) -> bool:
    p = os.path.abspath(path)
    had = any(pp == p for pp, _ in _PENDING_META)
    if had:
        _PENDING_META[:] = [(pp, m) for pp, m in _PENDING_META if pp != p]
    return had


def pending_meta_for(path: str) -> Optional[Dict[str, Any]]:
    """The deferred meta of an in-flight ASYNC save of `path`, if one is
    queued (a copy; the real one is published by the finalizer). Lets
    same-process readers — checkpoint retention deciding whether the
    newest save is blessed — see the stamps before meta.json lands,
    instead of misreading a streaming write as 'unknown'."""
    p = os.path.abspath(path)
    with _META_LOCK:
        for pp, meta in _PENDING_META:
            if pp == p:
                return dict(meta)
    return None


def discard_pending_meta(path: str) -> bool:
    """Forget the deferred meta for `path` (its checkpoint dir is being
    deleted). Returns True if an entry existed — i.e. the state write may
    still be streaming into that dir, so callers should join in-flight
    writes before removing it. If the finalizer thread is writing this
    path's meta/digest RIGHT NOW, blocks (bounded) until its hands are
    off the directory — an rmtree racing the digest walk would otherwise
    corrupt neither-here-nor-there state."""
    p = os.path.abspath(path)
    with _FIN_CV:
        had = _discard_locked(p)
        deadline = time.monotonic() + 60.0
        while p in _FINALIZING:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                log.warning("finalizer still writing %s after 60s; "
                            "proceeding with deletion", p)
                break
            _FIN_CV.wait(timeout=min(remaining, 1.0))
        return had


def wait_for_checkpoints() -> None:
    """Join all in-flight async checkpoint writes (no-op when none) and
    their meta.json finalizations. If any write failed, NO deferred meta
    is written (conservative: an un-finalized dir reads as no checkpoint)
    and the first recorded error propagates to the caller."""
    if _FIN_THREAD is not None and _FIN_THREAD.is_alive():
        _FIN_QUEUE.join()
    try:
        if _CKPT is not None:
            with _CK_LOCK:
                _CKPT.wait_until_finished()
    except Exception:
        with _META_LOCK:
            _PENDING_META.clear()
        raise
    _flush_pending_meta()
    _raise_recorded_errors()


def load_checkpoint(path: str) -> Dict[str, Any]:
    """Small-model convenience: restore everything to host-local arrays.

    Returns the state dict merged with parsed metadata (incl. "hparams").
    """
    path = os.path.abspath(path)
    state = _checkpointer().restore(os.path.join(path, _STATE_DIR))
    out = dict(state)
    out.update(_read_meta(path))
    return out


def restore_checkpoint(path: str, target: Any) -> Any:
    """Sharding-preserving restore: `target` is a pytree of jax.Arrays or
    ShapeDtypeStructs (with `.sharding` set) giving the layout to restore
    into — each host reads only its shards. Used for resume at scale."""
    path = os.path.abspath(path)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=getattr(x, "sharding", None)),
        target,
    )
    restored = _checkpointer().restore(os.path.join(path, _STATE_DIR),
                                       abstract)
    # Copy out of orbax/TensorStore-owned buffers before handing the tree
    # to callers: the Trainer DONATES its whole TrainState into the
    # jitted step, and donating a restored array whose buffer the
    # checkpoint runtime still references lets XLA reuse memory it does
    # not own — observed on the CPU backend as intermittent SIGSEGV /
    # SIGABRT mid-run and, worse, silently corrupted params after a
    # resume (flaky denormal garbage in the resumed weights). The same
    # no-donation identity protects the save direction (device_snapshot).
    return device_snapshot(restored)


def read_meta(path: str) -> Dict[str, Any]:
    return _read_meta(os.path.abspath(path))


def _read_meta(path: str) -> Dict[str, Any]:
    meta_path = os.path.join(path, _META_FILE)
    if not os.path.exists(meta_path):
        return {}
    with open(meta_path) as f:
        meta = json.load(f)
    hex_ = meta.pop("hparams_pickle_hex", None)
    if hex_:
        meta["hparams"] = pickle.loads(bytes.fromhex(hex_))
    return meta
