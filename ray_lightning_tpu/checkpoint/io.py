"""Sharded checkpoint I/O (orbax-backed).

Design requirement from SURVEY §3.4/§5.4: the reference round-trips full
state dicts through the driver (ray_ddp.py:186-193) and even ships whole
checkpoint dicts through a queue actor for Tune (tune.py:128-142) — a
scaling hazard it explicitly must NOT copy for 8B-param models. Here:

  * workers write *sharded* checkpoints in place (each host saves only its
    addressable shards — orbax handles the multi-host protocol);
  * only paths + small metadata travel between processes;
  * a small-model convenience path (`load_checkpoint`) gathers to host for
    the reference's `load_from_checkpoint` UX.

Layout of a checkpoint directory:
    <path>/state/     orbax pytree ({"params", "opt_state", "step"} or subset)
    <path>/meta.json  {epoch, global_step, module_class, hparams_pickle_hex,
                       ckpt_digest, ckpt_files, ckpt_digest_mode}

Atomicity & verifiability (the resilience subsystem's resume source of
truth, docs/RESILIENCE.md): orbax itself writes the state tree into a
temp dir and renames on finalize, so the state dir is never observable
half-written; meta.json — the "checkpoint is complete" marker — is
written AFTER the state finalizes, to a temp file + os.replace (atomic
on POSIX), and records a content digest of the finalized state files.
``latest_checkpoint(dir)`` walks candidates newest-first and returns the
first that VERIFIES — torn dirs (no meta), partial dirs (file-set
mismatch) and corrupt dirs (digest mismatch) are skipped, so a
supervisor resume can never load the checkpoint the crash tore.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
from typing import Any, Dict, List, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)

_STATE_DIR = "state"
_META_FILE = "meta.json"

# meta.json writes deferred until their async state write finalizes —
# meta.json presence is the "checkpoint is complete" marker, so it must
# never exist over a still-streaming (or failed) state dir.
_PENDING_META: List[Tuple[str, Dict[str, Any]]] = []

# Singleton: StandardCheckpointer is an AsyncCheckpointer — in-flight
# background writes must not be garbage-collected with a per-call
# instance, and wait_for_checkpoints() needs a handle to join them.
_CKPT: Optional[ocp.StandardCheckpointer] = None


def _checkpointer() -> ocp.StandardCheckpointer:
    global _CKPT
    if _CKPT is None:
        _CKPT = ocp.StandardCheckpointer()
    return _CKPT


def save_checkpoint(
    path: str,
    state: Dict[str, Any],
    meta: Optional[Dict[str, Any]] = None,
    block: bool = True,
) -> str:
    """Write `state` (pytree of possibly-sharded jax.Arrays) + metadata.

    Multi-host safe: every process must call this collectively; orbax
    writes each host's addressable shards.

    ``block=False`` returns as soon as the device->host copy is done and
    streams the disk write in the background (training continues during
    I/O — the big-model checkpoint stall killer); join with
    `wait_for_checkpoints()` before reading the files or exiting.
    """
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    meta = dict(meta or {})
    hparams = meta.pop("hparams", None)
    if hparams is not None:
        meta["hparams_pickle_hex"] = pickle.dumps(hparams).hex()
    ck = _checkpointer()
    ck.save(os.path.join(path, _STATE_DIR), state, force=True)
    if block:
        ck.wait_until_finished()
        # the join above finalized EVERY in-flight write, including earlier
        # async ones — flush their deferred metas too, then write ours
        _flush_pending_meta()
        _write_meta(path, meta)
    else:
        # meta.json is the completeness marker — defer it until
        # wait_for_checkpoints() confirms the state write finalized.
        _PENDING_META.append((path, meta))
    return path


#: digest policy (env RLT_CKPT_DIGEST): "full" hashes file contents —
#: the default, and what corrupt-checkpoint detection needs; "size"
#: hashes only (relpath, size) — cheap at 8B scale, still catches torn
#: and truncated files; "off" records no digest.
_DIGEST_MODE_ENV = "RLT_CKPT_DIGEST"


def _digest_mode() -> str:
    mode = os.environ.get(_DIGEST_MODE_ENV, "full")
    return mode if mode in ("full", "size", "off") else "full"


def compute_state_digest(path: str, mode: str = "full") -> Tuple[str, int]:
    """(sha256 hexdigest, file count) over the finalized state dir —
    deterministic: files visited in sorted relpath order."""
    state_dir = os.path.join(os.path.abspath(path), _STATE_DIR)
    h = hashlib.sha256()
    count = 0
    entries = []
    for root, dirs, files in os.walk(state_dir):
        dirs.sort()
        for f in sorted(files):
            full = os.path.join(root, f)
            entries.append((os.path.relpath(full, state_dir), full))
    for rel, full in sorted(entries):
        size = os.path.getsize(full)
        h.update(f"{rel}\x00{size}\x00".encode())
        count += 1
        if mode == "full":
            with open(full, "rb") as fh:
                for chunk in iter(lambda: fh.read(1 << 20), b""):
                    h.update(chunk)
    return h.hexdigest(), count


def _write_meta(path: str, meta: Dict[str, Any]) -> None:
    if jax.process_index() != 0:
        return
    meta = dict(meta)
    mode = _digest_mode()
    meta["ckpt_digest_mode"] = mode
    if mode != "off":
        try:
            digest, count = compute_state_digest(path, mode)
            meta["ckpt_digest"] = digest
            meta["ckpt_files"] = count
        except OSError:
            # a digest failure must not lose the checkpoint itself; the
            # meta lands digest-less and verification degrades to
            # presence checks
            log.exception("could not digest checkpoint %s", path)
    meta_path = os.path.join(path, _META_FILE)
    tmp = meta_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
    # atomic publish: a crash mid-write leaves only the tmp file and the
    # checkpoint reads as incomplete (no meta.json), never as torn JSON
    os.replace(tmp, meta_path)


def verify_checkpoint(path: str) -> Tuple[bool, str]:
    """Is this directory a complete, uncorrupted checkpoint?
    Returns (ok, reason) — reason names the first failed check."""
    path = os.path.abspath(path)
    state_dir = os.path.join(path, _STATE_DIR)
    if not os.path.isdir(state_dir):
        return False, "no state dir (write never started or was removed)"
    meta_path = os.path.join(path, _META_FILE)
    if not os.path.exists(meta_path):
        return False, "no meta.json (write never finalized — torn)"
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (OSError, ValueError) as exc:
        return False, f"unreadable meta.json ({exc})"
    recorded = meta.get("ckpt_digest")
    mode = meta.get("ckpt_digest_mode", "off")
    if recorded and mode in ("full", "size"):
        try:
            digest, count = compute_state_digest(path, mode)
        except OSError as exc:
            return False, f"state unreadable ({exc})"
        if count != meta.get("ckpt_files", count):
            return False, (f"partial state: {count} files on disk vs "
                           f"{meta.get('ckpt_files')} recorded")
        if digest != recorded:
            return False, "digest mismatch (corrupt or tampered state)"
    return True, "ok"


def latest_checkpoint(directory: str) -> Optional[str]:
    """Newest VALID checkpoint under ``directory`` (the dir itself is
    also considered, so both a checkpoint path and a dir of checkpoints
    work). Candidates ordered by recorded global_step (mtime breaks
    ties), newest first; torn/partial/corrupt candidates are skipped
    with a logged reason. None when nothing valid exists."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    candidates = []
    names = [directory] + [
        os.path.join(directory, d) for d in os.listdir(directory)
        if os.path.isdir(os.path.join(directory, d))
    ]
    for cand in names:
        if not os.path.isdir(os.path.join(cand, _STATE_DIR)):
            continue
        step = -1
        meta_path = os.path.join(cand, _META_FILE)
        try:
            with open(meta_path) as f:
                step = int(json.load(f).get("global_step", -1))
        except (OSError, ValueError, TypeError):
            pass  # still a candidate; verify_checkpoint rejects it below
        try:
            mtime = os.path.getmtime(cand)
        except OSError:
            continue
        candidates.append((step, mtime, cand))
    for _, _, cand in sorted(candidates, reverse=True):
        ok, reason = verify_checkpoint(cand)
        if ok:
            return cand
        log.warning("skipping invalid checkpoint %s: %s", cand, reason)
    return None


def _flush_pending_meta() -> None:
    global _PENDING_META
    pending, _PENDING_META = _PENDING_META, []
    for path, meta in pending:
        _write_meta(path, meta)


def discard_pending_meta(path: str) -> bool:
    """Forget the deferred meta for `path` (its checkpoint dir is being
    deleted). Returns True if an entry existed — i.e. the state write may
    still be streaming into that dir, so callers should join in-flight
    writes before removing it."""
    global _PENDING_META
    p = os.path.abspath(path)
    had = any(pp == p for pp, _ in _PENDING_META)
    if had:
        _PENDING_META = [(pp, m) for pp, m in _PENDING_META if pp != p]
    return had


def wait_for_checkpoints() -> None:
    """Join all in-flight async checkpoint writes (no-op when none), then
    finalize their meta.json markers. If any write failed, NO deferred meta
    is written (conservative: an un-finalized dir reads as no checkpoint)
    and the error propagates to the caller."""
    global _PENDING_META
    try:
        if _CKPT is not None:
            _CKPT.wait_until_finished()
    except Exception:
        _PENDING_META = []
        raise
    _flush_pending_meta()


def load_checkpoint(path: str) -> Dict[str, Any]:
    """Small-model convenience: restore everything to host-local arrays.

    Returns the state dict merged with parsed metadata (incl. "hparams").
    """
    path = os.path.abspath(path)
    state = _checkpointer().restore(os.path.join(path, _STATE_DIR))
    out = dict(state)
    out.update(_read_meta(path))
    return out


def restore_checkpoint(path: str, target: Any) -> Any:
    """Sharding-preserving restore: `target` is a pytree of jax.Arrays or
    ShapeDtypeStructs (with `.sharding` set) giving the layout to restore
    into — each host reads only its shards. Used for resume at scale."""
    path = os.path.abspath(path)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=getattr(x, "sharding", None)),
        target,
    )
    restored = _checkpointer().restore(os.path.join(path, _STATE_DIR),
                                       abstract)
    # Copy out of orbax/TensorStore-owned buffers before handing the tree
    # to callers: the Trainer DONATES its whole TrainState into the
    # jitted step, and donating a restored array whose buffer the
    # checkpoint runtime still references lets XLA reuse memory it does
    # not own — observed on the CPU backend as intermittent SIGSEGV /
    # SIGABRT mid-run and, worse, silently corrupted params after a
    # resume (flaky denormal garbage in the resumed weights). A jitted
    # identity without donation cannot alias its inputs, so it
    # materializes fresh runtime-owned buffers with the same shardings.
    return jax.jit(lambda t: t)(restored)


def read_meta(path: str) -> Dict[str, Any]:
    return _read_meta(os.path.abspath(path))


def _read_meta(path: str) -> Dict[str, Any]:
    meta_path = os.path.join(path, _META_FILE)
    if not os.path.exists(meta_path):
        return {}
    with open(meta_path) as f:
        meta = json.load(f)
    hex_ = meta.pop("hparams_pickle_hex", None)
    if hex_:
        meta["hparams"] = pickle.loads(bytes.fromhex(hex_))
    return meta
