from ray_lightning_tpu.checkpoint.io import (
    save_checkpoint,
    load_checkpoint,
    latest_checkpoint,
    restore_checkpoint,
    sharding_provenance,
    verify_checkpoint,
    wait_for_checkpoints,
)

__all__ = ["save_checkpoint", "load_checkpoint", "latest_checkpoint",
           "restore_checkpoint", "sharding_provenance",
           "verify_checkpoint", "wait_for_checkpoints"]
