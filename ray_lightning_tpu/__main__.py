"""``python -m ray_lightning_tpu`` — environment/topology doctor.

Pod-debugging UX the reference delegated to Ray's dashboard: one command
answers "what does THIS process see" — backend, process/device topology
(the rank helpers of SURVEY §5.8), per-device kind/slice, and optionally
a bare-matmul throughput probe that makes external contention on shared
chips visible (same probe bench.py embeds in its JSON).

    python -m ray_lightning_tpu            # topology, no device touch
    python -m ray_lightning_tpu --probe    # + matmul TFLOP/s
    python -m ray_lightning_tpu --json     # machine-readable
"""
from __future__ import annotations

import argparse
import json


def collect(probe: bool = False) -> dict:
    import jax

    devices = jax.devices()
    info = {
        "package": "ray_lightning_tpu 0.1.0",
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
        "devices": [
            {
                "id": d.id,
                "kind": d.device_kind,
                "platform": d.platform,
                "slice_index": getattr(d, "slice_index", None),
            }
            for d in devices[:16]
        ],
    }
    if len(devices) > 16:
        info["devices_truncated"] = len(devices) - 16
    if probe:
        import time

        import jax.numpy as jnp

        x = jnp.ones((4096, 4096), jnp.bfloat16)
        f = jax.jit(lambda a: a @ a)
        r = f(x)
        float(jax.device_get(r[0, 0]))
        t0 = time.perf_counter()
        for _ in range(10):
            r = f(r)
        float(jax.device_get(r[0, 0]))
        dt = (time.perf_counter() - t0) / 10
        info["probe_matmul_tflops"] = round(2 * 4096**3 / dt / 1e12, 1)
    return info


def main(argv=None) -> int:
    p = argparse.ArgumentParser("python -m ray_lightning_tpu")
    p.add_argument("--probe", action="store_true",
                   help="run a bare-matmul throughput probe (touches and "
                        "may briefly occupy the accelerator)")
    p.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args(argv)
    info = collect(probe=args.probe)
    if args.as_json:
        print(json.dumps(info))
        return 0
    print(f"{info['package']}  (jax {info['jax']}, "
          f"backend {info['backend']})")
    print(f"process {info['process_index']}/{info['process_count']}  "
          f"devices {info['local_devices']} local / "
          f"{info['global_devices']} global")
    for d in info["devices"]:
        sl = f" slice={d['slice_index']}" if d["slice_index"] is not None else ""
        print(f"  [{d['id']}] {d['kind']} ({d['platform']}){sl}")
    if info.get("devices_truncated"):
        print(f"  ... and {info['devices_truncated']} more")
    if "probe_matmul_tflops" in info:
        print(f"probe: {info['probe_matmul_tflops']} TFLOP/s bf16 matmul")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
