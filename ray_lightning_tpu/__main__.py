"""``python -m ray_lightning_tpu`` — environment/topology doctor + planner.

Pod-debugging UX the reference delegated to Ray's dashboard: one command
answers "what does THIS process see" — backend, process/device topology
(the rank helpers of SURVEY §5.8), per-device kind/slice, and optionally
a bare-matmul throughput probe that makes external contention on shared
chips visible (the same throughput-bound probe bench.py embeds in its
JSON, utils/probe.py).

    python -m ray_lightning_tpu            # topology, no device touch
    python -m ray_lightning_tpu --probe    # + matmul TFLOP/s
    python -m ray_lightning_tpu --json     # machine-readable

``plan`` runs the pre-flight memory planner (parallel/plan.py) with no
devices touched at all — size a model against a proposed mesh and chip
before queueing for hardware:

    python -m ray_lightning_tpu plan --preset llama3-8b \\
        --fsdp 64 --batch 64 --seq 8192 --device-kind "TPU v5p"

``lint`` runs shardcheck (analysis/): the pre-compile static analyzer
for sharding plans and jitted training code — mesh-axis typos, host
transfers inside training_step, Python RNG / wallclock / print in
traced code, unhashable static args. Zero hardware, target files are
parsed, never executed:

    python -m ray_lightning_tpu lint ray_lightning_tpu/models
    python -m ray_lightning_tpu lint my_project.module --json

``perf`` measures the hot-loop overlap machinery on THIS box (CPU-safe):
device-prefetch speedup with a calibrated synthetic slow loader, plus
the AOT warm-start compile metrics against the persistent compile
cache. ``--smoke`` is the format.sh gate (pipeline occupancy must be
> 0):

    python -m ray_lightning_tpu perf --smoke
    python -m ray_lightning_tpu perf --steps 80 --depth 4

``supervise`` runs a distributed fit under the resilience supervisor
(resilience/supervisor.py, docs/RESILIENCE.md): transient failures
restart the worker group and resume from the latest valid checkpoint;
trainguard corruption escalations roll back to the last blessed one.
``--smoke`` is the CPU fault-injection convergence gate format.sh runs
(worker kill + the trainguard legs: injected NaN must skip in-jit,
injected parameter bit-flip must quarantine the rank):

    python -m ray_lightning_tpu supervise --smoke
    python -m ray_lightning_tpu supervise my_project.jobs:make_job \\
        --processes 4 --max-restarts 3

``serve`` runs the continuous-batching inference engine (serve/,
docs/SERVING.md): a paged-KV decode engine multiplexed over replica
groups, with ``--smoke`` as the format.sh gate (8 concurrent streams
bitwise-identical to single-stream generate(), churn compiles once, an
injected replica SIGKILL auto-recovers, decode step audits clean):

    python -m ray_lightning_tpu serve example --replicas 2
    python -m ray_lightning_tpu serve llama3-8b --topo v5p-8
    python -m ray_lightning_tpu serve --smoke

``elastic`` runs the elastic-training smoke gate (elastic/,
docs/ELASTIC.md): an 8-device checkpoint must reshard-restore onto a
4-device mesh bitwise and keep training, and a supervised 2-process
run whose retry budget refuses a same-size relaunch must shrink onto
the survivor world and converge:

    python -m ray_lightning_tpu elastic --smoke

``autoscale`` runs the closed-loop serving autoscaler (autoscale/,
docs/AUTOSCALE.md): a pressure-band policy polling the serving load
signal and actuating replica count through the ServeDriver scaling
seams, with every decision in an append-only ledger. ``--smoke`` is
the format.sh gate (scripted ramp scales 1 -> 2 -> 1 with bitwise
streams, a capacity clamp + SIGKILL-absorbing spawn drill, and the
all-draining submit deferral):

    python -m ray_lightning_tpu autoscale
    python -m ray_lightning_tpu autoscale --smoke

``loadgen`` runs the trace-driven load harness (loadgen/,
docs/SERVING.md "traffic & SLO classes"): seeded Poisson/bursty-MMPP
workload traces with heavy-tailed lengths and a traffic-class mix,
generated or recorded as versioned JSONL and replayed bitwise against
the real serving stack with priority/SLO-aware scheduling armed.
``--smoke`` is the format.sh gate (byte-deterministic traces, a
bursty mixed-class replay that sheds best-effort with typed records
while latency-critical meets its TTFT SLO, a class-scoped incident,
zero silent drops, compile count pinned at 1 on both backends):

    python -m ray_lightning_tpu loadgen --out trace.jsonl --seed 7
    python -m ray_lightning_tpu loadgen --trace trace.jsonl
    python -m ray_lightning_tpu loadgen --smoke

``report`` / ``monitor`` read the telemetry a run left behind
(telemetry/, docs/OBSERVABILITY.md): the goodput classification of
supervised wall time, per-rank span timelines, and — with
``--preset/--topo`` — the drift section joining the measured timeline
against tracecheck's prediction. ``monitor --smoke`` is the format.sh
observability gate (telemetry=off byte-identical pin, fault-injected
goodput report sums to wall, flagship drift section emits):

    python -m ray_lightning_tpu report rlt_logs --preset llama3-8b \\
        --topo v5p-64
    python -m ray_lightning_tpu monitor rlt_logs --follow
    python -m ray_lightning_tpu monitor --smoke

``timeline`` merges EVERY evidence ledger a run dir holds — spans,
goodput attempts, serving metrics ticks, flight rings, autoscale
decisions, reshards, incidents — into one causally-ordered stream
(telemetry/timeline.py, docs/OBSERVABILITY.md "unified timeline");
``--chrome`` exports Chrome-trace/Perfetto JSON so the whole run opens
as one trace:

    python -m ray_lightning_tpu timeline rlt_logs
    python -m ray_lightning_tpu timeline rlt_logs --chrome trace.json

``watch`` evaluates the declarative SLO rules (telemetry/watch.py:
ttft_p99, goodput_fraction, queue pressure, guard streaks, restart
rate) over a run dir's persisted evidence; a sustained breach appends
a self-documenting record to incidents.jsonl (metric evidence + a
timeline excerpt) and actuates the evidence hooks (profiler CAPTURE
marker, forced flight persist). ``--smoke`` is the format.sh gate (an
injected serving latency stall must fire the ttft rule exactly once
and the run's timeline must export as a valid multi-source Chrome
trace):

    python -m ray_lightning_tpu watch rlt_logs --follow
    python -m ray_lightning_tpu watch --smoke

Exit status: 0 when the plan fits, 1 when it does not, 2 when the
configuration is invalid (e.g. a global batch not divisible by the
data-parallel degree — refused rather than planned wrong; the error goes
to stderr, or an {"error": ...} object with --json).
"""
from __future__ import annotations

import argparse
import json
import sys


def collect(probe: bool = False) -> dict:
    import jax

    devices = jax.devices()
    info = {
        "package": "ray_lightning_tpu 0.1.0",
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
        "devices": [
            {
                "id": d.id,
                "kind": d.device_kind,
                "platform": d.platform,
                "slice_index": getattr(d, "slice_index", None),
            }
            for d in devices[:16]
        ],
    }
    if len(devices) > 16:
        info["devices_truncated"] = len(devices) - 16
    if probe:
        from ray_lightning_tpu.utils.probe import (
            PEAK_TFLOPS,
            device_peak_tflops,
            matmul_tflops,
        )

        info["probe_matmul_tflops"] = round(matmul_tflops(), 1)
        info["peak_tflops"] = device_peak_tflops(devices[0].device_kind)
        # unknown kinds get the v5e-class fallback — label it honestly
        info["peak_is_assumed"] = devices[0].device_kind not in PEAK_TFLOPS
    return info


def _plan_invalid(msg: str, as_json: bool) -> int:
    """The documented exit-status contract: every invalid configuration
    exits 2 with a structured error, distinguishable by scripted
    consumers from the meaningful exit-1 'does not fit' verdict."""
    if as_json:
        print(json.dumps({"error": msg}))
    else:
        print(f"error: {msg}", file=sys.stderr)
    return 2


def _plan_trace_section(args, module_factory, strategy_factory,
                        n_devices: int, global_batch: int):
    """tracecheck the planned step (jaxpr-level collective/HBM audit,
    analysis/tracecheck.py) — the plan's byte math says whether the
    weights FIT; this section says what the step will DO: ICI bytes and
    estimated peak HBM. Degrades to a trace_error field rather than
    failing the plan (the plan verdict must survive an audit bug)."""
    import numpy as np

    try:
        from ray_lightning_tpu.analysis.costmodel import topology_for_kind
        from ray_lightning_tpu.analysis.tracecheck import audit_step

        topo = topology_for_kind(args.device_kind, n_devices,
                                 hbm_bytes=args.hbm_bytes)
        report = audit_step(
            module_factory(), strategy_factory(),
            {"tokens": np.zeros((global_batch, args.seq + 1), np.int32)},
            topology=topo, label=f"{args.preset} plan")
        counts = {"error": 0, "warning": 0, "note": 0}
        for f in report.findings:
            counts[f.severity] += 1
        return {
            "ici_bytes_per_step": report.ici_bytes_per_step,
            "ici_time_us": round(report.ici_time_us, 1),
            "ici_hidden_us": round(report.ici_hidden_us, 1),
            "ici_exposed_us": round(report.ici_exposed_us, 1),
            "overlap_hidden_fraction": round(
                report.overlap_hidden_fraction, 4),
            "overlap_scheduled": bool(
                (report.overlap or {}).get("scheduled")),
            "peak_hbm_bytes": report.peak_hbm_bytes,
            "hbm_budget_bytes": report.hbm_budget_bytes,
            "fits": report.fits,
            "finding_counts": counts,
            "findings": [f.to_dict() for f in report.findings],
            **({"precision": report.precision}
               if getattr(args, "precision", False) else {}),
        }
    except Exception as exc:  # noqa: BLE001 — advisory section only
        return {"trace_error": f"{type(exc).__name__}: {str(exc)[:300]}"}


def _print_trace_section(trace: dict) -> None:
    if "trace_error" in trace:
        print(f"tracecheck: unavailable ({trace['trace_error']})")
        return
    gib = 1024**3
    print(f"tracecheck: ICI {trace['ici_bytes_per_step'] / gib:.2f} "
          f"GiB/step (~{trace['ici_time_us'] / 1e3:.1f} ms serialized), "
          f"est. peak HBM {trace['peak_hbm_bytes'] / gib:.2f} GiB vs "
          f"budget {trace['hbm_budget_bytes'] / gib:.2f} GiB "
          f"({'fits' if trace['fits'] else 'DOES NOT FIT'})")
    print(f"  overlap: "
          f"{'prefetch schedule' if trace.get('overlap_scheduled') else 'no prefetch schedule'}"
          f" — {trace.get('overlap_hidden_fraction', 0.0):.0%} of "
          f"prefetchable collective time hidden "
          f"({trace.get('ici_hidden_us', 0.0) / 1e3:.1f} ms hidden, "
          f"{trace.get('ici_exposed_us', 0.0) / 1e3:.1f} ms exposed)")
    for f in trace["findings"]:
        print(f"  {f['severity']} {f['rule']} ({f['name']}): "
              f"{f['message']}")
    _print_precision_ledger(trace.get("precision"))


def _print_precision_ledger(prec) -> None:
    """``plan --precision``: the per-dtype-class byte ledger numcheck
    fills on every TraceReport (analysis/numcheck.py)."""
    if not prec:
        return
    mib = 1024**2

    def _cls(name):
        by = prec.get(name) or {}
        if not by:
            return "-"
        return ", ".join(f"{dt} {b / mib:.1f} MiB"
                         for dt, b in sorted(by.items(),
                                             key=lambda kv: -kv[1]))
    print("  precision ledger (per device):")
    for name in ("params", "opt_state", "activations", "kv_pool"):
        print(f"    {name:<12} {_cls(name)}")
    print(f"    loss widest-path dtype: "
          f"{prec.get('loss_widest_dtype') or 'n/a'}")


def _run_serve_plan(args) -> int:
    """``plan --serve``: the serving replica's HBM story (no optimizer
    — weights + paged KV pool + the attention path's gathered view +
    carried logits) with the decode-step tracecheck section. The
    attention path is auto-selected by shape: when the fused
    paged-attention kernel tiles the config the plan prices the fused
    path and states the per-replica HBM the kernel retired
    (docs/SERVING.md "paged-attention kernel"); the decode-step trace
    audits the SAME path. Same exit contract as the training plan: 0
    fits, 1 does not, 2 invalid."""
    import dataclasses

    import jax.numpy as jnp

    from ray_lightning_tpu.models.llama import LlamaConfig
    from ray_lightning_tpu.serve.audit import (
        audit_decode_step,
        format_serve_summary,
        serve_memory_summary,
        shared_prefix_plan,
        speculative_plan,
    )
    from ray_lightning_tpu.serve.engine import EngineConfig

    for name in ("serve_slots", "serve_block_size", "tp"):
        if getattr(args, name) < 1:
            return _plan_invalid(
                f"--{name.replace('_', '-')} must be >= 1, got "
                f"{getattr(args, name)}", args.as_json)
    presets = {
        "llama3-8b": LlamaConfig.llama3_8b,
        "tiny": LlamaConfig.tiny,
    }
    cfg = presets[args.preset](max_seq_len=args.seq, dtype=jnp.bfloat16)
    bps = -(-args.seq // args.serve_block_size)
    try:
        ecfg = EngineConfig(
            capacity=args.serve_slots,
            block_size=args.serve_block_size, blocks_per_slot=bps,
            prefill_chunk=min(max(128, args.serve_block_size),
                              args.seq))
        summary = serve_memory_summary(
            cfg, ecfg, device_kind=args.device_kind,
            hbm_bytes=args.hbm_bytes, tp=args.tp)
        # static pricing for the scheduler's two decode accelerators:
        # prefix sharing across a full fleet of slots, and speculative
        # decoding against a quarter-depth draft at the default k
        draft_cfg = dataclasses.replace(
            cfg, n_layers=max(1, cfg.n_layers // 4))
        prefix = shared_prefix_plan(cfg, ecfg,
                                    n_streams=args.serve_slots)
        spec = speculative_plan(cfg, draft_cfg, ecfg)
    except ValueError as exc:
        return _plan_invalid(str(exc), args.as_json)
    trace = None
    if not args.no_trace:
        try:
            from ray_lightning_tpu.analysis.costmodel import (
                topology_for_kind,
            )

            topo = topology_for_kind(args.device_kind, 1,
                                     hbm_bytes=args.hbm_bytes)
            fused = summary["attention_path"] == "paged-pallas"
            report = audit_decode_step(cfg, ecfg, topology=topo,
                                       label=f"{args.preset} serve",
                                       fused=fused, tp=args.tp)
            trace = {
                "attention_path": summary["attention_path"],
                "peak_hbm_bytes": report.peak_hbm_bytes,
                "hbm_budget_bytes": report.hbm_budget_bytes,
                "findings": [f.to_dict() for f in report.findings],
                **({"precision": report.precision}
                   if getattr(args, "precision", False) else {}),
            }
            if args.tp > 1:
                # the decode step's collective schedule over the
                # replica group's own mesh — the per-tick ICI story
                # `bench --static`'s serve_tp section and the bench
                # gate's serve_decode_ici_bytes_per_tick ratchet read
                trace["collectives"] = [
                    {"kind": e.kind, "axes": list(e.axes),
                     "payload_bytes": e.payload_bytes,
                     "count": e.count, "wire_bytes": e.wire_bytes,
                     "source": e.source,
                     **({"param": e.param_path} if e.param_path
                        else {})}
                    for e in report.collectives]
                trace["decode_ici_bytes_per_tick"] = sum(
                    e.wire_bytes for e in report.collectives)
        except Exception as exc:  # noqa: BLE001 — advisory section only
            trace = {"trace_error":
                     f"{type(exc).__name__}: {str(exc)[:300]}"}
    if args.as_json:
        out = {"serve": summary, "fits": summary["fits"],
               "prefix_sharing": prefix, "speculative": spec}
        if trace is not None:
            out["trace"] = trace
        print(json.dumps(out))
    else:
        print(format_serve_summary(summary))
        mib = 1024.0**2
        print(f"prefix sharing ({prefix['n_streams']} streams, "
              f"{prefix['prefix_tokens']}-token prefix): pool bytes "
              f"saved {prefix['shared_pool_bytes_saved'] / mib:.1f} "
              f"MiB; prefill tokens saved "
              f"{prefix['prefill_tokens_saved']}")
        print(f"speculative (k={spec['k']}, accept "
              f"{spec['accept_rate']:.2f}): verify step "
              f"{spec['verify_step_flops'] / 1e9:.2f} GFLOP vs "
              f"{spec['k']} base ticks "
              f"{spec['k'] * spec['base_decode_flops_per_token'] / 1e9:.2f}"
              f" GFLOP; expected tokens/tick "
              f"{spec['expected_tokens_per_tick']:.2f}; memory-bound "
              f"speedup {spec['memory_bound_speedup_x']:.2f}x")
        if trace is not None:
            if "trace_error" in trace:
                print(f"tracecheck: unavailable ({trace['trace_error']})")
            else:
                gib = 1024**3
                rules = sorted({f["rule"] for f in trace["findings"]})
                print(f"tracecheck (decode step): liveness peak "
                      f"{trace['peak_hbm_bytes'] / gib:.2f} GiB vs "
                      f"budget {trace['hbm_budget_bytes'] / gib:.2f} "
                      f"GiB; findings: {rules if rules else 'none'}")
                if trace.get("collectives") is not None:
                    kib = 1024.0
                    print("  decode collectives (per tick, one "
                          "replica group):")
                    for ev in trace["collectives"]:
                        print(f"    {ev['kind']:<11} "
                              f"x{ev['count']:<3} "
                              f"{ev['payload_bytes'] / kib:8.1f} KiB  "
                              f"wire {ev['wire_bytes'] / kib:8.1f} "
                              f"KiB  {ev['source']}")
                    ici_kib = trace["decode_ici_bytes_per_tick"] / kib
                    print(f"    ICI bytes/tick: {ici_kib:.1f} KiB")
                _print_precision_ledger(trace.get("precision"))
    return 0 if summary["fits"] else 1


def run_plan(args) -> int:
    import numpy as np

    from ray_lightning_tpu.models.llama import LlamaConfig, LlamaModule
    from ray_lightning_tpu.parallel.mesh import MeshSpec
    from ray_lightning_tpu.parallel.plan import (
        dp_degree,
        find_max_local_batch,
        llama_activation_bytes,
        llama_overlap_buffer_bytes,
        plan_train_memory,
    )
    from ray_lightning_tpu.parallel.strategy import ShardedMesh

    presets = {
        "llama3-8b": LlamaConfig.llama3_8b,
        "tiny": LlamaConfig.tiny,
    }
    if args.serve:
        return _run_serve_plan(args)
    # --find-max-batch ignores --batch entirely, including its validation
    checked = ("data", "fsdp", "tensor", "seq") if args.find_max_batch \
        else ("data", "fsdp", "tensor", "batch", "seq")
    for name in checked:
        if getattr(args, name) < 1:
            # a zero/negative axis would ZeroDivisionError below — exit 2,
            # never a traceback colliding with the exit-1 verdict
            return _plan_invalid(
                f"--{name} must be >= 1, got {getattr(args, name)}",
                args.as_json,
            )
    cfg = presets[args.preset](
        remat=True, scan_layers=True, fused_ce=True, max_seq_len=args.seq,
        ce_inline_bwd=args.ce_inline_bwd,
    )

    def _module():
        import jax.numpy as jnp

        return LlamaModule(
            cfg, mu_dtype=jnp.bfloat16 if args.mu_bf16 else None)

    def _strategy():
        return ShardedMesh(data=args.data, fsdp=args.fsdp,
                           tensor=args.tensor, overlap=args.overlap)
    # the double-buffer HBM the overlap schedule holds beyond the naive
    # ZeRO path — charged on top of the activation bound so RLT302 /
    # the FITS verdict stay honest with overlap= on (and named in the
    # output: a surprise half-GiB would otherwise hide in "acts")
    overlap_bytes = llama_overlap_buffer_bytes(
        cfg, fsdp=args.fsdp, tensor=args.tensor, mode=args.overlap) \
        if args.overlap != "off" else 0

    def _print_overlap_bytes():
        if not overlap_bytes:
            return
        what = ("in-flight grad shard — serial ablation: no double "
                "buffer, no rolled xs" if args.overlap == "serial" else
                "one prefetched layer gathered over fsdp + rolled xs "
                "shard + in-flight grad shard")
        print(f"overlap double-buffer: "
              f"{overlap_bytes / 1024**2:.1f} MiB/device ({what}) "
              f"charged in the activation bound")
    n_devices = args.data * args.fsdp * args.tensor
    dp = dp_degree(MeshSpec(data=args.data, fsdp=args.fsdp,
                            tensor=args.tensor))
    if not args.find_max_batch and args.batch % dp != 0:
        # a clamped/floored local batch would produce a FITS verdict for
        # a job that cannot actually shard its batch — refuse up front
        return _plan_invalid(
            f"global batch {args.batch} is not divisible by the "
            f"data-parallel degree {dp} (data x fsdp); the job could "
            f"not shard this batch. Pick batch = k x {dp}.",
            args.as_json,
        )
    try:
        if args.find_max_batch:
            # auto_scale_batch_size, plan-side: search the activation
            # bound against the HBM left after the batch-independent
            # weight costs — no devices, no failed compiles
            local, plan = find_max_local_batch(
                _module(),
                _strategy(),
                n_devices=n_devices,
                example_batch={"tokens": np.zeros((dp, args.seq + 1),
                                                  np.int32)},
                activation_bytes_fn=lambda b: llama_activation_bytes(
                    cfg, b, args.seq,
                    weight_shard_degree=args.fsdp * args.tensor)
                + overlap_bytes,
                device_kind=args.device_kind,
                hbm_bytes_per_device=args.hbm_bytes,
            )
            # local==0 returns the activation-free plan, whose own
            # summary can read FITS (the weights fit; no batch does) —
            # label it so no consumer reads a contradiction
            summary = plan.summary() if local >= 1 else (
                "no local batch fits — weights-only plan: "
                + plan.summary())
            result = {
                "max_local_batch": local,
                "max_global_batch": local * dp,
                "dp_degree": dp,
                "fits": local >= 1,
                "overlap": args.overlap,
                "overlap_buffer_bytes": overlap_bytes,
                "summary": summary,
            }
            trace = None
            if local >= 1 and not args.no_trace:
                trace = _plan_trace_section(
                    args, _module, _strategy, n_devices, local * dp)
                result["trace"] = trace
            if args.as_json:
                print(json.dumps(result))
            else:
                print(f"max batch: {local}/device x dp {dp} = "
                      f"{local * dp} global")
                print(summary)
                _print_overlap_bytes()
                if trace is not None:
                    _print_trace_section(trace)
            return 0 if local >= 1 else 1
        plan = plan_train_memory(
            _module(),
            _strategy(),
            n_devices=n_devices,
            example_batch={"tokens": np.zeros((args.batch, args.seq + 1),
                                              np.int32)},
            activation_bytes_per_device=llama_activation_bytes(
                cfg, args.batch // dp, args.seq,
                weight_shard_degree=args.fsdp * args.tensor)
            + overlap_bytes,
            device_kind=args.device_kind,
            hbm_bytes_per_device=args.hbm_bytes,
        )
    except ValueError as exc:
        # a mesh the strategy rejects, a planner refusal — same contract
        return _plan_invalid(str(exc), args.as_json)
    trace = None
    if not args.no_trace:
        trace = _plan_trace_section(
            args, _module, _strategy, n_devices, args.batch)
    if args.as_json:
        out = {
            "mesh": plan.mesh_axes,
            "n_devices": plan.n_devices,
            "per_device_bytes": plan.per_device_total,
            "budget_bytes": plan.budget,
            "fits": plan.fits,
            "overlap": args.overlap,
            "overlap_buffer_bytes": overlap_bytes,
            "summary": plan.summary(),
        }
        if trace is not None:
            out["trace"] = trace
        print(json.dumps(out))
    else:
        print(plan.summary())
        _print_overlap_bytes()
        if trace is not None:
            _print_trace_section(trace)
    return 0 if plan.fits else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser("python -m ray_lightning_tpu")
    p.add_argument("--probe", action="store_true",
                   help="run a bare-matmul throughput probe (touches and "
                        "may briefly occupy the accelerator)")
    p.add_argument("--json", action="store_true", dest="as_json")
    sub = p.add_subparsers(dest="cmd")
    plan_p = sub.add_parser(
        "plan", help="pre-flight memory plan for a model x mesh x chip "
                     "(no devices touched)")
    plan_p.add_argument("--preset", choices=("llama3-8b", "tiny"),
                        default="llama3-8b")
    plan_p.add_argument("--data", type=int, default=1)
    plan_p.add_argument("--fsdp", type=int, default=64)
    plan_p.add_argument("--tensor", type=int, default=1)
    plan_p.add_argument("--batch", type=int, default=64,
                        help="global batch (rows)")
    plan_p.add_argument("--seq", type=int, default=8192)
    plan_p.add_argument("--device-kind", default="TPU v5p",
                        help="PJRT device_kind string (e.g. 'TPU v5p'); "
                             "unknown kinds error with the known list "
                             "unless --hbm-bytes is given")
    plan_p.add_argument("--hbm-bytes", type=int, default=None,
                        help="per-device usable HBM override in bytes — "
                             "plan hardware the built-in table doesn't "
                             "know (any --device-kind is then accepted)")
    plan_p.add_argument("--ce-inline-bwd", action="store_true",
                        help="plan with the inline-backward fused CE "
                             "(charges its dx + sharded dW residuals)")
    plan_p.add_argument("--overlap", choices=("off", "on", "serial"),
                        default="off",
                        help="plan with the collective-overlap schedule "
                             "(docs/PERFORMANCE.md): charges the double-"
                             "buffer HBM and traces the overlapped step")
    plan_p.add_argument("--mu-bf16", action="store_true",
                        help="plan with a bf16 Adam first moment "
                             "(mu_dtype=bfloat16 — halves the mu buffer; "
                             "the planner charges the real dtype)")
    plan_p.add_argument("--serve", action="store_true",
                        help="plan a SERVING replica instead of a "
                             "training step: weights + paged KV pool + "
                             "gathered view vs the chip budget, with "
                             "the decode-step tracecheck section "
                             "(docs/SERVING.md)")
    plan_p.add_argument("--serve-slots", type=int, default=8,
                        help="serving slot capacity (plan --serve)")
    plan_p.add_argument("--serve-block-size", type=int, default=16,
                        help="KV pool block size in tokens "
                             "(plan --serve)")
    plan_p.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel degree of ONE serving "
                             "replica (plan --serve): prices one rank "
                             "of the replica group — per-shard params "
                             "+ pool HBM and the decode step's "
                             "collective schedule over the replica's "
                             "own mesh (docs/SERVING.md 'sharded "
                             "replicas')")
    plan_p.add_argument("--find-max-batch", action="store_true",
                        help="ignore --batch and report the largest "
                             "per-device batch (and the implied global "
                             "batch) that fits this mesh/chip — "
                             "auto_scale_batch_size without touching "
                             "hardware")
    # SUPPRESS: the subparser parses into the SAME namespace the parent
    # already filled — a plain default=False here would overwrite a
    # `--json` given before the subcommand
    plan_p.add_argument("--json", action="store_true", dest="as_json",
                        default=argparse.SUPPRESS)
    plan_p.add_argument("--no-trace", action="store_true",
                        help="skip the tracecheck section (the "
                             "jaxpr-level collective/HBM audit of the "
                             "planned step)")
    plan_p.add_argument("--precision", action="store_true",
                        help="include numcheck's precision ledger in "
                             "the trace section: per-dtype bytes for "
                             "params / opt state / activations / KV "
                             "pool and the loss's widest-path dtype "
                             "(docs/STATIC_ANALYSIS.md)")
    from ray_lightning_tpu.analysis.cli import (
        add_lint_parser, add_trace_parser, run_lint, run_trace,
    )
    from ray_lightning_tpu.autoscale.cli import (
        add_autoscale_parser, run_autoscale,
    )
    from ray_lightning_tpu.elastic.cli import (
        add_elastic_parser, run_elastic,
    )
    from ray_lightning_tpu.loadgen.cli import (
        add_loadgen_parser, run_loadgen,
    )
    from ray_lightning_tpu.pipeline.cli import add_perf_parser, run_perf
    from ray_lightning_tpu.resilience.cli import (
        add_supervise_parser, run_supervise,
    )
    from ray_lightning_tpu.serve.cli import add_serve_parser, run_serve
    from ray_lightning_tpu.telemetry.report import (
        add_monitor_parser, add_report_parser, run_monitor, run_report,
    )
    from ray_lightning_tpu.telemetry.timeline import (
        add_timeline_parser, run_timeline,
    )
    from ray_lightning_tpu.telemetry.watch import (
        add_watch_parser, run_watch,
    )

    add_lint_parser(sub)
    add_trace_parser(sub)
    add_supervise_parser(sub)
    add_perf_parser(sub)
    add_serve_parser(sub)
    add_report_parser(sub)
    add_monitor_parser(sub)
    add_timeline_parser(sub)
    add_watch_parser(sub)
    add_elastic_parser(sub)
    add_autoscale_parser(sub)
    add_loadgen_parser(sub)
    args = p.parse_args(argv)
    if args.cmd == "plan":
        return run_plan(args)
    if args.cmd == "lint":
        return run_lint(args)
    if args.cmd == "trace":
        return run_trace(args)
    if args.cmd == "supervise":
        return run_supervise(args)
    if args.cmd == "perf":
        return run_perf(args)
    if args.cmd == "serve":
        return run_serve(args)
    if args.cmd == "report":
        return run_report(args)
    if args.cmd == "monitor":
        return run_monitor(args)
    if args.cmd == "timeline":
        return run_timeline(args)
    if args.cmd == "watch":
        return run_watch(args)
    if args.cmd == "elastic":
        return run_elastic(args)
    if args.cmd == "autoscale":
        return run_autoscale(args)
    if args.cmd == "loadgen":
        return run_loadgen(args)
    info = collect(probe=args.probe)
    if args.as_json:
        print(json.dumps(info))
        return 0
    print(f"{info['package']}  (jax {info['jax']}, "
          f"backend {info['backend']})")
    print(f"process {info['process_index']}/{info['process_count']}  "
          f"devices {info['local_devices']} local / "
          f"{info['global_devices']} global")
    for d in info["devices"]:
        sl = f" slice={d['slice_index']}" if d["slice_index"] is not None else ""
        print(f"  [{d['id']}] {d['kind']} ({d['platform']}){sl}")
    if info.get("devices_truncated"):
        print(f"  ... and {info['devices_truncated']} more")
    if "probe_matmul_tflops" in info:
        label = "assumed peak" if info["peak_is_assumed"] else "spec peak"
        print(f"probe: {info['probe_matmul_tflops']} TFLOP/s bf16 matmul "
              f"({label} {info['peak_tflops']})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
