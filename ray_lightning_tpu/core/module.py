"""TpuModule — the Lightning-style user-facing model protocol, made functional.

The reference delegated this entirely to PyTorch Lightning's LightningModule
(its test models exercise the full hook surface: tests/utils.py:26-93 in the
reference). The rebuild owns the protocol. Differences are deliberate and
TPU-first:

  * steps are *pure functions of (params, batch, rng)* so the Trainer can
    `jax.jit` them over a sharded mesh with donated state;
  * `self.log(...)` works inside a traced step (values are collected during
    tracing and returned as part of the compiled step's metrics output);
  * params live beside the module (`module.params`), not inside it, keeping
    the (static module def) / (array state) split that XLA serialization
    needs (cf. SURVEY §7.4 hard part 3).
"""
from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import optax

Metrics = Dict[str, jnp.ndarray]
StepOutput = Union[jnp.ndarray, Tuple[jnp.ndarray, Metrics]]

#: the hooks the Trainer compiles under jax.jit — their bodies run under
#: a tracer, so host transfers / Python RNG / wallclock inside them are
#: per-step bugs. The shardcheck linter (analysis/linter.py) treats
#: these names, and everything they call, as traced code; the tuple
#: lives in analysis/findings.py (dependency-free) and is re-exported
#: here as the protocol constant.
from ray_lightning_tpu.analysis.findings import (  # noqa: E402,F401
    TRACED_STEP_HOOKS,
)


class TpuModule:
    """Subclass and implement the `configure_*` / `*_step` hooks.

    Required:
        configure_model()       -> a flax.linen Module (or None for raw-param
                                   modules that implement init_params/apply)
        configure_optimizers()  -> optax.GradientTransformation
        training_step(params, batch, rng) -> loss | (loss, metrics)

    Optional:
        validation_step(params, batch) -> metrics dict
        test_step(params, batch)       -> metrics dict (defaults to validation_step)
        predict_step(params, batch)    -> predictions
        init_params(rng, batch)        -> params pytree
        param_specs(params)            -> {path: PartitionSpec} for tensor/seq axes
        on_fit_start/on_fit_end(trainer)
        on_train_epoch_start/on_train_epoch_end(trainer)
        on_validation_epoch_end(trainer, metrics)
        on_save_checkpoint(checkpoint) / on_load_checkpoint(checkpoint)
    """

    def __init__(self) -> None:
        self.model = None          # flax module, set by configure_model()
        self.params: Any = None    # trained weights land here after fit (C5)
        self.trainer = None        # backref set by Trainer during fit
        self.mesh = None           # bound by Strategy.setup before setup()
        self.overlap = False       # strategy overlap= knob (collective
        #                            prefetch schedule; models that have
        #                            an overlapped path honor it)
        self.hparams: Dict[str, Any] = {}
        self._logged: Dict[str, jnp.ndarray] = {}

    # ---- required hooks --------------------------------------------------

    def configure_model(self):
        return None

    def configure_optimizers(self) -> optax.GradientTransformation:
        return optax.adam(1e-3)

    def training_step(self, params, batch, rng) -> StepOutput:
        raise NotImplementedError

    # ---- optional hooks --------------------------------------------------

    def validation_step(self, params, batch) -> Metrics:
        raise NotImplementedError

    def test_step(self, params, batch) -> Metrics:
        return self.validation_step(params, batch)

    def predict_step(self, params, batch):
        raise NotImplementedError

    def param_specs(self, params) -> Optional[Dict[str, Any]]:
        return None

    def on_fit_start(self, trainer) -> None: ...
    def on_fit_end(self, trainer) -> None: ...
    def on_train_epoch_start(self, trainer) -> None: ...
    def on_train_epoch_end(self, trainer) -> None: ...
    def on_validation_epoch_end(self, trainer, metrics: Metrics) -> None: ...
    def on_save_checkpoint(self, checkpoint: dict) -> None: ...
    def on_load_checkpoint(self, checkpoint: dict) -> None: ...

    # ---- provided machinery ---------------------------------------------

    def setup(self) -> None:
        """Idempotently build the inner flax module."""
        if self.model is None:
            self.model = self.configure_model()

    def init_params(self, rng, batch) -> Any:
        """Default init: feed the batch's first leaf (or 'x'/inputs key)."""
        if self.model is None:
            raise NotImplementedError(
                "Provide configure_model() or override init_params()."
            )
        x = _example_input(batch)
        variables = self.model.init(rng, x)
        return variables["params"]

    def apply(self, params, *args, rngs=None, **kwargs):
        """Call the inner flax module: `self.apply(params, x)`."""
        if self.model is None:
            raise RuntimeError(
                f"{type(self).__name__}.model is not built. If setup() "
                "has not run yet, call it (Trainer.fit / "
                "load_from_checkpoint do); if it has, configure_model() "
                "returned None — implement it (or override apply())."
            )
        return self.model.apply({"params": params}, *args, rngs=rngs, **kwargs)

    def log(self, name: str, value) -> None:
        """Record a metric from inside a traced step (Lightning's self.log).

        Values logged during tracing are hoisted into the compiled step's
        metric outputs and land in `trainer.callback_metrics`.
        """
        self._logged[name] = jnp.asarray(value)

    def log_dict(self, metrics: Dict[str, Any]) -> None:
        for k, v in metrics.items():
            self.log(k, v)

    def pop_logged(self) -> Dict[str, jnp.ndarray]:
        out, self._logged = self._logged, {}
        return out

    def num_params(self) -> int:
        assert self.params is not None, "no params; fit or init first"
        import numpy as np

        return sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(self.params))

    def save_hyperparameters(self, **kwargs) -> None:
        """Record ctor kwargs for `load_from_checkpoint` reconstruction.

        With no kwargs, captures the caller's (the subclass __init__'s)
        local arguments by inspection, like Lightning's version.
        """
        if not kwargs:
            frame = inspect.currentframe().f_back
            args = {
                k: v
                for k, v in frame.f_locals.items()
                if k not in ("self", "__class__") and not k.startswith("_")
            }
            kwargs = args
        self.hparams.update(kwargs)

    @classmethod
    def load_from_checkpoint(cls, path: str, **override_hparams) -> "TpuModule":
        """Reconstruct a module + weights from a checkpoint directory.

        Parity: `Model.load_from_checkpoint(best_model_path)` in the
        reference tests (tests/utils.py:184-189).
        """
        from ray_lightning_tpu.checkpoint import load_checkpoint

        ckpt = load_checkpoint(path)
        hparams = dict(ckpt.get("hparams") or {})
        hparams.update(override_hparams)
        module = cls(**hparams)
        module.setup()
        module.params = ckpt["params"]
        module.on_load_checkpoint(ckpt)
        return module

    @classmethod
    def lint(cls, **lint_kwargs):
        """shardcheck this module class's source file: the AST linter
        (analysis/linter.py) over the file that defines the subclass —
        host transfers / Python RNG / wallclock / print inside the
        traced step hooks, mesh-axis typos in PartitionSpec literals.

        Returns a list of `analysis.Finding`; empty means clean. The
        plan-side audit (spec composition, opt dtypes, donation) needs a
        strategy and lives in `analysis.check_plan(module, strategy,
        n_devices, example_batch)`.
        """
        import inspect

        from ray_lightning_tpu.analysis import lint_paths

        src = inspect.getsourcefile(cls)
        if src is None:  # dynamically-built class: nothing to parse
            return []
        return lint_paths([src], **lint_kwargs)

    def audit_step(self, strategy, example_batch, *, topology="v5p-8",
                   **kw):
        """tracecheck this module's real jitted train step under
        ``strategy`` on ``topology`` — the jaxpr-level sibling of
        `lint()` (source) and `analysis.check_plan` (specs): collective
        schedule + ICI cost, implicit-resharding findings, ring checks,
        and a peak-HBM estimate, all without touching hardware. See
        `Strategy.audit_step`; the strategy instance is consumed."""
        return strategy.audit_step(self, example_batch,
                                   topology=topology, **kw)

    # Convenience: module(batch) runs predict with stored params.
    def __call__(self, *args, **kwargs):
        if self.params is None:
            raise RuntimeError("Module has no params; fit or load a checkpoint.")
        return self.apply(self.params, *args, **kwargs)


def _example_input(batch):
    if isinstance(batch, dict):
        for key in ("x", "inputs", "input_ids", "image", "images"):
            if key in batch:
                return batch[key]
        return next(iter(batch.values()))
    if isinstance(batch, (tuple, list)):
        return batch[0]
    return batch
