"""Callback bus: EarlyStopping, ModelCheckpoint, progress/throughput logging.

The reference leaned on PTL for all of these and only *transported* their
effects (rank-0 best_model_path round-trip, ray_ddp.py:186-193,280-291;
checkpoint hooks verified by test_early_stop, reference tests/utils.py:89-93,
tests/test_ddp.py:116-132). The rebuild owns them.
"""
from __future__ import annotations

import math
import os
import time
from typing import Any, Dict, Optional

import numpy as np

from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)


class Callback:
    def on_fit_start(self, trainer, module) -> None: ...
    def on_fit_end(self, trainer, module) -> None: ...
    def on_train_epoch_start(self, trainer, module) -> None: ...
    def on_train_batch_start(self, trainer, module, batch,
                             batch_idx: int):
        """Before the step dispatches. Return a (device) batch to
        REPLACE the one about to be trained on, or None to leave it —
        the fault injector's batch-poisoning kinds use this seam."""
        return None

    def on_train_batch_end(self, trainer, module, metrics: Dict[str, Any],
                           batch_idx: int) -> None: ...
    def on_train_epoch_end(self, trainer, module) -> None: ...
    def on_validation_epoch_end(self, trainer, module,
                                metrics: Dict[str, Any]) -> None: ...
    def on_save_checkpoint(self, trainer, module, checkpoint: dict) -> None: ...
    def on_load_checkpoint(self, trainer, module, checkpoint: dict) -> None: ...
    def on_exception(self, trainer, module, exc: BaseException) -> None: ...


class EarlyStopping(Callback):
    """Stop when `monitor` stops improving (PTL-compatible surface)."""

    def __init__(self, monitor: str = "val_loss", patience: int = 3,
                 mode: str = "min", min_delta: float = 0.0):
        assert mode in ("min", "max")
        self.monitor = monitor
        self.patience = patience
        self.mode = mode
        self.min_delta = min_delta
        self.best = math.inf if mode == "min" else -math.inf
        self.wait = 0

    def _improved(self, value: float) -> bool:
        if self.mode == "min":
            return value < self.best - self.min_delta
        return value > self.best + self.min_delta

    def _check(self, trainer, metrics: Dict[str, Any]) -> None:
        if self.monitor not in metrics:
            return
        value = float(metrics[self.monitor])
        if self._improved(value):
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                log.info("EarlyStopping: %s=%g (best %g), stopping",
                         self.monitor, value, self.best)
                trainer.should_stop = True

    def on_validation_epoch_end(self, trainer, module, metrics) -> None:
        self._check(trainer, metrics)

    def on_train_epoch_end(self, trainer, module) -> None:
        if not trainer.has_validation:
            self._check(trainer, trainer.callback_metrics)


class ModelCheckpoint(Callback):
    """Track-and-save the best (and/or last) checkpoint.

    After fit, `best_model_path` is readable on the driver — the reference
    shipped this string from worker rank 0 (ray_ddp.py:186-193); here the
    trainer owns the loop so it is simply set in place.
    """

    def __init__(self, dirpath: Optional[str] = None, monitor: Optional[str] = None,
                 mode: str = "min", save_top_k: int = 1, save_last: bool = False,
                 every_n_epochs: int = 1,
                 every_n_train_steps: Optional[int] = None,
                 filename: str = "epoch={epoch}",
                 async_save: bool = False):
        self.dirpath = dirpath
        self.monitor = monitor
        self.mode = mode
        self.save_top_k = save_top_k
        self.save_last = save_last
        self.every_n_epochs = max(1, every_n_epochs)
        #: step-based cadence (LLM-style long epochs); saves are
        #: unmonitored at step boundaries (metrics lag validation)
        self.every_n_train_steps = every_n_train_steps
        self.filename = filename
        #: async_save=True streams the disk write in the background
        #: (checkpoint/io.py block=False); the Trainer joins in-flight
        #: writes at fit end.
        self.async_save = async_save
        self.best_model_path: str = ""
        self.best_model_score: Optional[float] = None
        self.last_model_path: str = ""
        self._saved: list[tuple[float, str]] = []  # (score, path)

    def _resolve_dir(self, trainer) -> str:
        d = self.dirpath or os.path.join(trainer.default_root_dir, "checkpoints")
        os.makedirs(d, exist_ok=True)
        return d

    def _score(self, metrics: Dict[str, Any]) -> Optional[float]:
        if self.monitor is None:
            return None
        if self.monitor not in metrics:
            return None
        return float(metrics[self.monitor])

    def _maybe_save(self, trainer, module, metrics: Dict[str, Any],
                    step_based: bool = False) -> None:
        if not step_based and trainer.current_epoch % self.every_n_epochs != 0:
            return
        d = self._resolve_dir(trainer)
        name = self.filename.format(epoch=trainer.current_epoch,
                                    step=trainer.global_step)
        if step_based and "{step" not in self.filename:
            name = f"step={trainer.global_step}"
        elif (not step_based and trainer.val_check_interval
                and "{step" not in self.filename):
            # mid-epoch validation (val_check_interval) saves several times
            # per epoch; disambiguate the default epoch-only filename so
            # saves don't overwrite each other within an epoch
            name = f"{name}-step={trainer.global_step}"
        path = os.path.join(d, name)
        if step_based:
            # step cadence ignores `monitor` (metrics lag validation):
            # recency-tracked like the unmonitored path, pruned to
            # save_top_k so long runs stay disk-bounded.
            self._dedupe(path)
            trainer.save_checkpoint(path, block=not self.async_save)
            self.best_model_path = path
            if self.save_last:
                self.last_model_path = path
            self._saved.append((-float(trainer.global_step), path))
            self._prune(trainer)
            return
        score = self._score(metrics)
        if self.monitor is not None and score is None:
            return  # monitored metric absent this epoch
        self._dedupe(path)
        trainer.save_checkpoint(path, block=not self.async_save)
        if self.save_last:
            self.last_model_path = path
        if self.monitor is None:
            # Unmonitored: "best" is the most recent; prune to save_top_k.
            self.best_model_path = path
            self._saved.append((-float(trainer.global_step), path))
            self._prune(trainer)
            return
        sign = 1.0 if self.mode == "min" else -1.0
        self._saved.append((sign * score, path))
        if self.best_model_score is None or sign * score < sign * self.best_model_score:
            self.best_model_score = score
            self.best_model_path = path
        self._prune(trainer)

    def _dedupe(self, path: str) -> None:
        # re-saving an existing path must replace, not duplicate, its
        # _saved entry — duplicates distort save_top_k accounting. Called
        # only on the branches that actually save to `path`.
        self._saved = [(s, p) for s, p in self._saved if p != path]

    def _prune(self, trainer=None) -> None:
        if self.save_top_k <= 0:
            return
        self._saved.sort(key=lambda t: t[0])
        keep = self._saved[: self.save_top_k]
        stale = self._saved[self.save_top_k:]
        # Retention floor (trainguard, docs/RESILIENCE.md): a corruption
        # rollback needs a checkpoint that is (a) explicitly blessed —
        # NOT saved inside an anomaly window; an unreadable/absent
        # blessing reads as "not known good", never as "safe to delete
        # the fallback" — and, when the SDC probe is armed, (b) at or
        # below the last probe-VERIFIED step (an SDC bit-flip is silent,
        # so newer checkpoints are blessed yet possibly poisoned). When
        # no kept checkpoint qualifies, the best-ranked stale one that
        # does is protected from pruning: a long anomaly streak (or a
        # probe cadence longer than the prune window) must never GC the
        # last good restore point.
        horizon = getattr(trainer, "_guard_probe_ok_step", None) \
            if trainer is not None else None

        def rollback_ok(path: str, max_step) -> bool:
            blessed, step = _ckpt_meta(path)
            if blessed is not True:
                return False
            return max_step is None or (step is not None
                                        and step <= max_step)

        protected: list[tuple[float, str]] = []
        if stale and keep:
            for need in ([None, horizon] if horizon is not None
                         else [None]):
                retained = keep + protected
                if not any(rollback_ok(p, need) for _, p in retained):
                    hit = next(
                        (e for e in stale
                         if e not in protected and rollback_ok(e[1], need)),
                        None)
                    if hit is not None:
                        protected.append(hit)
        protected_paths = {p for _, p in protected}
        for _, stale_path in stale:
            if stale_path in protected_paths:
                continue
            if stale_path not in (self.best_model_path,
                                  self.last_model_path):
                _remove_checkpoint(stale_path)
        self._saved = keep + protected

    def on_train_batch_end(self, trainer, module, metrics, batch_idx) -> None:
        if (self.every_n_train_steps
                and trainer.global_step % self.every_n_train_steps == 0):
            self._maybe_save(trainer, module, trainer.callback_metrics,
                             step_based=True)

    def on_validation_epoch_end(self, trainer, module, metrics) -> None:
        # cadences are mutually exclusive (PTL semantics): a step-based
        # checkpoint never also saves at epoch boundaries
        if not self.every_n_train_steps:
            self._maybe_save(trainer, module, metrics)

    def on_train_epoch_end(self, trainer, module) -> None:
        if not trainer.has_validation and not self.every_n_train_steps:
            self._maybe_save(trainer, module, trainer.callback_metrics)


class ThroughputMonitor(Callback):
    """Step-time / examples-per-sec — the §5.5 gap in the reference (it had
    no system metrics at all). Feeds trainer.callback_metrics.

    Cold-compile skew: without AOT warm start (``warm_start=False``, or
    a shape drift re-trace) the FIRST measured interval contains the
    lazy XLA compile — seconds against millisecond steps — and a
    window-mean over it misreports steps/s for the next ``window``
    batches. The first ``skip_first`` intervals of each fit are dropped,
    so the reported window is warm-only, consistent with the telemetry
    timeline's warm-step stats (telemetry/report.py drops the cold step
    the same way). ``clock`` is injectable for deterministic tests."""

    def __init__(self, window: int = 20, skip_first: int = 1,
                 clock=None):
        self.window = window
        self.skip_first = max(0, skip_first)
        self._clock = clock or time.perf_counter
        self._times: list[float] = []
        self._t0: Optional[float] = None
        self._intervals_seen = 0

    def on_fit_start(self, trainer, module) -> None:
        # a resumed/re-fit trainer re-pays its (possibly lazy) compile:
        # the skip window re-arms per fit, not per construction
        self._times = []
        self._t0 = None
        self._intervals_seen = 0

    def on_train_epoch_start(self, trainer, module) -> None:
        self._t0 = self._clock()

    def on_train_batch_end(self, trainer, module, metrics, batch_idx) -> None:
        t = self._clock()
        if self._t0 is not None:
            self._intervals_seen += 1
            if self._intervals_seen > self.skip_first:
                self._times.append(t - self._t0)
                self._times = self._times[-self.window:]
        self._t0 = t
        if self._times:
            step_time = float(np.mean(self._times))
            trainer.callback_metrics["step_time_s"] = step_time
            bs = trainer.last_batch_size
            if bs:
                trainer.callback_metrics["examples_per_sec"] = bs / step_time


class MemoryMonitor(Callback):
    """Per-epoch device HBM stats (bytes in use / peak) from PJRT's
    ``memory_stats`` — §5.5 observability the reference lacked entirely.
    Feeds ``hbm_bytes_in_use`` / ``hbm_peak_bytes`` into callback_metrics
    and logs them; silently inert on backends without memory_stats (CPU)."""

    def __init__(self, log_stats: bool = True):
        self.log_stats = log_stats

    @staticmethod
    def _stats() -> Optional[dict]:
        import jax

        try:
            stats = jax.local_devices()[0].memory_stats()
        except Exception:  # noqa: BLE001 — interface is backend-optional
            return None
        return stats or None

    def on_train_epoch_end(self, trainer, module) -> None:
        stats = self._stats()
        if stats is None:
            return
        in_use = stats.get("bytes_in_use")
        peak = stats.get("peak_bytes_in_use")
        if in_use is not None:
            trainer.callback_metrics["hbm_bytes_in_use"] = float(in_use)
        if peak is not None:
            trainer.callback_metrics["hbm_peak_bytes"] = float(peak)
        if self.log_stats and peak is not None:
            log.info("epoch %d HBM peak %.2f GiB (in use %.2f GiB)",
                     trainer.current_epoch, peak / 2**30,
                     (in_use or 0) / 2**30)


class ProgressLogger(Callback):
    """Console progress (the reference inherited PTL's bar; headless here)."""

    def __init__(self, log_every_n_steps: int = 50):
        self.every = max(1, log_every_n_steps)

    def on_train_batch_end(self, trainer, module, metrics, batch_idx) -> None:
        if trainer.global_step % self.every == 0:
            pretty = {k: (f"{float(v):.4g}" if np.ndim(v) == 0 else "…")
                      for k, v in metrics.items()}
            log.info("epoch %d step %d %s", trainer.current_epoch,
                     trainer.global_step, pretty)


def _ckpt_meta(path: str):
    """(blessed, global_step) from a checkpoint's meta.json — the
    trainguard blessing is True/False when stamped, None when absent or
    unreadable (pre-guard checkpoints and foreign dirs read as "not
    known good", which the retention floor treats conservatively). An
    in-flight ASYNC save whose meta.json has not landed yet is resolved
    from this process's deferred-meta queue, so the newest save never
    misreads as unknown and inflates retention."""
    import json

    try:
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
    except (OSError, ValueError):
        from ray_lightning_tpu.checkpoint.io import pending_meta_for

        meta = pending_meta_for(path)
        if meta is None:
            return None, None
    blessed = meta.get("blessed")
    try:
        step = int(meta.get("global_step"))
    except (TypeError, ValueError):
        step = None
    return (None if blessed is None else bool(blessed)), step


def _ckpt_blessed(path: str):
    return _ckpt_meta(path)[0]


def _remove_checkpoint(path: str) -> None:
    """Delete a pruned checkpoint dir, safely against in-flight async
    writes: if its state write is still streaming, join it first (else
    orbax's background finalize could resurrect the dir, or a deferred
    meta.json write could land in a deleted directory)."""
    from ray_lightning_tpu.checkpoint.io import (
        discard_pending_meta,
        wait_for_checkpoints,
    )

    if discard_pending_meta(path):
        try:
            wait_for_checkpoints()
        except Exception:  # noqa: BLE001
            # the failed write may concern a KEPT checkpoint, but its
            # pending meta was already dropped by wait_for_checkpoints'
            # conservative error path — nothing more to do than log
            log.exception("async checkpoint write failed during prune")
    _rmtree_quiet(path)


def _rmtree_quiet(path: str) -> None:
    import shutil

    try:
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)
    except OSError:
        pass
