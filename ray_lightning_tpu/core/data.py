"""Data loading: host-side batch iterators feeding the sharded step.

Replaces the reference's DataLoader + forced DistributedSampler
(ray_lightning/ray_ddp.py:293-303: num_replicas=num_workers,
rank=global_rank, shuffle per-epoch). TPU-first differences:

  * batches are pytrees of numpy arrays with a *global* leading batch dim;
    the Strategy turns them into mesh-sharded `jax.Array`s;
  * in multi-process mode each host yields only its shard (the sampler
    semantics) and the global array is assembled from per-process shards;
  * static shapes: `drop_last` defaults to True so every step compiles once.
"""
from __future__ import annotations

import os
from typing import Any, Iterable, Iterator, Optional

import numpy as np


class DataLoader:
    """Minimal array-backed loader: shuffling, batching, per-epoch reseed.

    `data` is a pytree (dict/tuple) of equal-length numpy arrays, or a
    callable epoch->iterable for streaming sources.
    """

    def __init__(
        self,
        data: Any,
        batch_size: int = 1,
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = True,
        num_shards: int = 1,
        shard_index: int = 0,
        prefetch: bool = False,
        num_workers: Optional[int] = None,
        sharded_externally: bool = False,
    ):
        self.data = data
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.num_shards = num_shards
        self.shard_index = shard_index
        self.prefetch = prefetch
        #: declares that ``data`` already holds/yields just THIS
        #: process's rows (per-host files, a pre-split array, a
        #: sharding-aware stream) — `ensure_sharded` then leaves the
        #: loader alone instead of injecting num_shards on top.
        self.sharded_externally = sharded_externally
        self._num_workers = num_workers
        self._batcher = None
        self._epoch = 0
        self._stream = callable(data)
        if self._stream:
            self._n = None
            return
        leaves = _leaves(data)
        if not leaves:
            raise ValueError("empty dataset")
        self._n = len(leaves[0])
        for leaf in leaves:
            if len(leaf) != self._n:
                raise ValueError("all arrays must share leading dim")

    @property
    def num_workers(self) -> int:
        """Prefetch thread-pool size. Resolved LAZILY so a strategy's env
        injection (RayXlaPlugin num_cpus_per_worker → RLT_NUM_CPUS_PER_WORKER,
        reference ray_ddp.py:89-111) applies even when the loader was
        constructed before Trainer.fit ran strategy.setup()."""
        if self._num_workers is not None:
            return max(1, self._num_workers)
        return max(1, int(os.environ.get("RLT_NUM_CPUS_PER_WORKER", 2)))

    def set_epoch(self, epoch: int) -> None:
        """Reference parity: DistributedSampler.set_epoch reshuffles per epoch."""
        self._epoch = epoch

    def __len__(self) -> int:
        if self._stream:
            raise TypeError("streaming DataLoader has no length")
        n = self._n // self.num_shards
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def __iter__(self) -> Iterator[Any]:
        if self._stream:
            epoch, self._epoch = self._epoch, self._epoch + 1
            yield from self.data(epoch)
            return
        idx = np.arange(self._n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            rng.shuffle(idx)
        # contiguous equal-size shard per process (the DistributedSampler
        # analog; equal sizes keep __len__ and step counts consistent
        # across ranks — remainder examples are dropped)
        if self.num_shards > 1:
            per = self._n // self.num_shards
            shard = idx[self.shard_index * per : (self.shard_index + 1) * per]
        else:
            shard = idx
        if self.prefetch and (batcher := self._get_batcher()) is not None:
            # native path: worker threads assemble batches ahead of the
            # loop (ray_lightning_tpu/native/batcher.cpp); same order,
            # same shapes as the numpy path below.
            batcher.set_epoch(shard)
            yield from batcher
            self._epoch += 1
            return
        n = len(shard)
        stop = n - n % self.batch_size if self.drop_last else n
        for start in range(0, stop, self.batch_size):
            take = shard[start : start + self.batch_size]
            yield _tree_take(self.data, take)
        self._epoch += 1

    def _get_batcher(self):
        """Lazily build the native prefetcher; None when ineligible (non-
        dict pytrees, non-numpy leaves) or the toolchain is unavailable."""
        if self._batcher is not None:
            return self._batcher
        if not isinstance(self.data, dict) or not all(
            isinstance(v, np.ndarray)
            and (np.issubdtype(v.dtype, np.number) or v.dtype == np.bool_)
            for v in self.data.values()
        ):
            return None  # object/string leaves can't cross the C ABI
        try:
            from ray_lightning_tpu.native import NativeBatcher

            self._batcher = NativeBatcher(
                self.data, self.batch_size, drop_last=self.drop_last,
                n_threads=self.num_workers,
            )
        except (RuntimeError, ValueError):
            self.prefetch = False  # don't retry every epoch
            return None
        return self._batcher


class ThrottledLoader:
    """Wrap a loader with a fixed per-batch host delay.

    The deliberately-slow synthetic loader behind the prefetch-overlap
    evidence (pipeline/overlap.py, bench.py, ``python -m
    ray_lightning_tpu perf``): real input pipelines pay tokenization /
    decode / augmentation time per batch, which a CPU benchmark box
    doesn't naturally have — ``delay_s`` stands in for it, so the
    device-prefetch win is measurable anywhere. Also a testing hook: a
    known per-batch cost makes backpressure and overlap assertions
    deterministic.

    Forwards ``set_epoch``/``__len__`` so it drops into every place a
    `DataLoader` does.
    """

    def __init__(self, inner: Any, delay_s: float):
        self.inner = inner
        self.delay_s = float(delay_s)

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.inner, "set_epoch"):
            self.inner.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.inner)

    def __iter__(self) -> Iterator[Any]:
        import time

        for batch in self.inner:
            if self.delay_s > 0:
                time.sleep(self.delay_s)
            yield batch


class DataModule:
    """Optional Lightning-style data container."""

    def setup(self) -> None: ...

    def train_dataloader(self) -> Iterable: ...

    def val_dataloader(self) -> Optional[Iterable]:
        return None

    def test_dataloader(self) -> Optional[Iterable]:
        return None

    def predict_dataloader(self) -> Optional[Iterable]:
        return None


def _leaves(data):
    if isinstance(data, dict):
        return list(data.values())
    if isinstance(data, (tuple, list)):
        return list(data)
    return [data]


def _tree_take(data, idx):
    if isinstance(data, dict):
        return {k: np.asarray(v)[idx] for k, v in data.items()}
    if isinstance(data, (tuple, list)):
        return type(data)(np.asarray(v)[idx] for v in data)
    return np.asarray(data)[idx]


def resolve_loaders(module, data) -> tuple:
    """Accept a DataModule or (train, val) iterables and normalize."""
    if isinstance(data, DataModule):
        data.setup()
        return data.train_dataloader(), data.val_dataloader()
    return data, None


def ensure_sharded(loader: Any, num_shards: int, shard_index: int,
                   stage: str = "train") -> Any:
    """Force distributed shard semantics onto a loader — the rebuild of
    the reference's *forced* DistributedSampler (ray_ddp.py:293-303:
    num_replicas=num_workers, rank=global_rank, injected whether or not
    the user thought about it), because the failure mode of forgetting is
    silent: `make_array_from_process_local_data` happily assembles a
    global batch where every host contributed identical rows — duplicated
    samples, no error, wrong training.

    Returns the loader with ``num_shards``/``shard_index`` set. Raises on
    anything it cannot make safe:
      * a `DataLoader` already sharded differently (user misconfiguration
        — two sources of truth for the shard layout);
      * a streaming `DataLoader` whose callable we cannot reach into,
        unless constructed with ``sharded_externally=True``;
      * a plain iterable (list/generator), which has no shard handle at
        all — wrap it in a `DataLoader`.
    """
    if loader is None or num_shards <= 1:
        return loader
    if isinstance(loader, DataLoader):
        if loader.sharded_externally:
            # The user declares this loader already yields only THIS
            # process's rows (its own per-host files, a pre-split array,
            # a sharding-aware stream) — honored for array-backed and
            # streaming sources alike; injecting num_shards on top would
            # silently train on a 1/world slice of each host's data.
            return loader
        if loader._stream:
            raise ValueError(
                f"streaming {stage} DataLoader in a {num_shards}-process "
                "job: the data callable is opaque, so per-process "
                "sharding cannot be injected. Make the callable yield "
                "only this process's rows (jax.process_index()) and "
                "construct the DataLoader with sharded_externally=True."
            )
        if loader.num_shards == 1:
            loader.num_shards = num_shards
            loader.shard_index = shard_index
            return loader
        if (loader.num_shards == num_shards
                and loader.shard_index == shard_index):
            return loader  # user already sharded it correctly — idempotent
        raise ValueError(
            f"{stage} DataLoader is sharded {loader.shard_index}/"
            f"{loader.num_shards} but this job runs as process "
            f"{shard_index}/{num_shards}. Drop the manual num_shards/"
            "shard_index arguments (the distributed launcher injects "
            "them) or make them match the job."
        )
    raise TypeError(
        f"{stage} data in a {num_shards}-process job must be a "
        f"ray_lightning_tpu DataLoader (got {type(loader).__name__}): a "
        "plain iterable has no shard handle, so every process would "
        "train on identical rows. Wrap the data in DataLoader(...)."
    )
