from ray_lightning_tpu.core.module import TpuModule
from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.core.state import TrainState
from ray_lightning_tpu.core.data import DataLoader, DataModule
from ray_lightning_tpu.core.text import (
    chunk_tokens,
    pack_sequences,
    tokenize_and_pack,
)
from ray_lightning_tpu.core.callbacks import (
    Callback,
    EarlyStopping,
    ModelCheckpoint,
    ProgressLogger,
    MemoryMonitor,
    ThroughputMonitor,
)

__all__ = [
    "TpuModule",
    "Trainer",
    "TrainState",
    "DataLoader",
    "DataModule",
    "chunk_tokens",
    "pack_sequences",
    "tokenize_and_pack",
    "Callback",
    "EarlyStopping",
    "ModelCheckpoint",
    "ProgressLogger",
    "MemoryMonitor",
    "ThroughputMonitor",
]
