"""Trainer: owns the jitted SPMD train/eval loops.

The reference borrowed this entirely from PyTorch Lightning and only hosted
it remotely (DDPSpawnPlugin.new_process invoked at reference
ray_ddp.py:238-241). The rebuild owns the loop, TPU-first:

  * ONE compiled program per step: `jax.value_and_grad` + optax update fused
    under `jax.jit`, full TrainState donated so params/opt-state update in
    place in HBM;
  * sharding by annotation: the Strategy places state/batches on the mesh,
    XLA emits the collectives (grad psum over `data`, FSDP all-gather /
    reduce-scatter over `fsdp`) — no process group, no explicit allreduce;
  * static shapes: dataloaders drop ragged tails so the step compiles once;
  * gradient accumulation via `lax.scan` over a microbatch axis (no Python
    loop inside jit);
  * metrics come back as device scalars and are fetched lazily to avoid a
    host sync per step.

API parity (C2 of SURVEY §7.1): fit/validate/test/predict, callbacks,
checkpointing, early stopping — everything the reference's BoringModel
exercises (reference tests/utils.py:26-93).
"""
from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Dict, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_lightning_tpu.checkpoint import (
    restore_checkpoint,
    save_checkpoint,
    wait_for_checkpoints,
)
from ray_lightning_tpu.checkpoint.io import read_meta
from ray_lightning_tpu.core.callbacks import (
    Callback,
    ModelCheckpoint,
    ProgressLogger,
)
from ray_lightning_tpu.core.data import DataModule
from ray_lightning_tpu.core.module import TpuModule
from ray_lightning_tpu.core.state import TrainState
from ray_lightning_tpu.parallel.strategy import SingleDevice, Strategy
from ray_lightning_tpu.pipeline.compile_cache import (
    WarmStep,
    enable_persistent_cache,
)
from ray_lightning_tpu.pipeline.prefetch import (
    DevicePrefetcher,
    prefetch_to_device,
)
from ray_lightning_tpu.telemetry import TelemetryConfig
from ray_lightning_tpu.telemetry import goodput as _goodput
from ray_lightning_tpu.telemetry.profiler import (
    ProfileConfig,
    ProfilerController,
)
from ray_lightning_tpu.telemetry.spans import (
    NULL_RECORDER,
    PH_CKPT,
    PH_DISPATCH,
    PH_EVAL,
    PH_METRICS,
    PH_RESHARD,
    PH_STEP,
    TelemetryRecorder,
)
from ray_lightning_tpu.utils import get_logger, seed_everything

log = get_logger(__name__)


class Trainer:
    def __init__(
        self,
        strategy: Optional[Strategy] = None,
        max_epochs: int = 1,
        max_steps: int = -1,
        callbacks: Optional[List[Callback]] = None,
        limit_train_batches: Optional[int] = None,
        limit_val_batches: Optional[int] = None,
        limit_test_batches: Optional[int] = None,
        check_val_every_n_epoch: int = 1,
        val_check_interval: Optional[int] = None,
        log_every_n_steps: int = 50,
        accumulate_grad_batches: int = 1,
        gradient_clip_val: Optional[float] = None,
        precision: str = "f32",  # "f32" | "bf16" (cast float inputs)
        seed: Optional[int] = None,
        default_root_dir: Optional[str] = None,
        enable_checkpointing: bool = True,
        enable_progress_bar: bool = True,
        profiler_dir: Optional[str] = None,
        num_sanity_val_steps: int = 0,
        prefetch_to_device: int = 2,
        warm_start: bool = True,
        compile_cache_dir: Optional[str] = None,
        guard: Any = None,
        telemetry: Any = None,
        profile: Any = None,
    ):
        self.strategy = strategy or SingleDevice()
        self.max_epochs = max_epochs
        self.max_steps = max_steps
        self.limit_train_batches = limit_train_batches
        self.limit_val_batches = limit_val_batches
        self.limit_test_batches = limit_test_batches
        self.check_val_every_n_epoch = max(1, check_val_every_n_epoch)
        #: mid-epoch validation every N optimizer steps (long-epoch /
        #: streaming LLM runs where epoch boundaries are meaningless)
        self.val_check_interval = val_check_interval
        self.log_every_n_steps = log_every_n_steps
        self.accumulate_grad_batches = max(1, accumulate_grad_batches)
        self.gradient_clip_val = gradient_clip_val
        self.precision = precision
        self.seed = seed
        self.default_root_dir = default_root_dir or os.path.join(
            os.getcwd(), "rlt_logs"
        )
        self.profiler_dir = profiler_dir
        self.num_sanity_val_steps = num_sanity_val_steps
        #: device-prefetch buffer depth (pipeline/prefetch.py): a
        #: background stage overlaps host batch assembly + sharded
        #: device_put with the previous step's compute. 0 disables
        #: (fully synchronous placement, bitwise-identical training).
        self.prefetch_to_device = max(0, prefetch_to_device)
        #: AOT-compile the train step at fit start (lower().compile(),
        #: pipeline/compile_cache.py) so compile time is a reported
        #: metric, not a mysteriously slow first batch; the eval step
        #: warms on its first batch. Shape drift falls back to lazy jit.
        self.warm_start = warm_start
        #: persistent XLA compilation cache dir; restarts (resilience
        #: supervisor) then deserialize the step instead of recompiling.
        self.compile_cache_dir = compile_cache_dir
        #: trainguard (resilience/guard.py): True / GuardConfig compiles
        #: finiteness + loss-spike checks INTO the train step — an
        #: anomalous update is discarded by a tree-select, the counters
        #: ride the existing metric outputs (no new host syncs), and a
        #: GuardCallback escalates sustained anomalies / SDC verdicts.
        self.guard = guard
        #: trainguard rollback marker payload (set by the supervisor's
        #: worker wrapper): after a corruption rollback, resume advances
        #: the data order past the poisoned window instead of replaying
        #: it. Applied in _init_state when the restore point is behind
        #: the marker's detection step.
        self.resume_skip_past: Optional[Dict[str, Any]] = None
        #: telemetry (telemetry/, docs/OBSERVABILITY.md): True /
        #: TelemetryConfig arms the host-side span recorder — data wait,
        #: H2D, dispatch, metric fetch, ckpt stall, compile, eval spans
        #: into a bounded ring flushed as per-rank JSONL on the logging
        #: cadence. Host bookkeeping only: telemetry=off compiles the
        #: byte-identical device program (test-pinned).
        self.telemetry = telemetry
        #: on-demand jax.profiler capture (telemetry/profiler.py):
        #: ProfileConfig(step window / marker file / SIGUSR1), rank-scoped
        self.profile = profile
        self.telemetry_recorder = NULL_RECORDER
        self._profiler: Optional[ProfilerController] = None
        self._telemetry_flush_every = 50
        self._fit_start_perf: Optional[float] = None
        self._fit_start_step = 0
        self._launch_s = 0.0

        self.callbacks: List[Callback] = list(callbacks or [])
        if enable_checkpointing and not any(
            isinstance(c, ModelCheckpoint) for c in self.callbacks
        ):
            self.callbacks.append(ModelCheckpoint())
        if enable_progress_bar and not any(
            isinstance(c, ProgressLogger) for c in self.callbacks
        ):
            self.callbacks.append(ProgressLogger(log_every_n_steps))

        # run state
        self.state: Optional[TrainState] = None
        self.module: Optional[TpuModule] = None
        self.tx: Optional[optax.GradientTransformation] = None
        self.callback_metrics: Dict[str, Any] = {}
        self.current_epoch = 0
        self.global_step = 0
        self.should_stop = False
        self.has_validation = False
        self._last_val_step = -1
        # mid-epoch bookkeeping for checkpoint/resume: whether we are
        # inside a partially-consumed train epoch, how many batches of the
        # current epoch ran, and how many to skip after a mid-epoch resume
        self._mid_epoch = False
        self._epoch_batches_done = 0
        self._resume_skip_batches = 0
        self.last_batch_size: Optional[int] = None
        self._train_step = None
        self._eval_step = None
        self._base_rng = None
        self.is_fitted = False

    # ------------------------------------------------------------------ fit

    @property
    def checkpoint_callback(self) -> Optional[ModelCheckpoint]:
        for c in self.callbacks:
            if isinstance(c, ModelCheckpoint):
                return c
        return None

    def fit(
        self,
        module: TpuModule,
        train_dataloaders: Optional[Iterable] = None,
        val_dataloaders: Optional[Iterable] = None,
        datamodule: Optional[DataModule] = None,
        ckpt_path: Optional[str] = None,
    ) -> Dict[str, Any]:
        seed = seed_everything(self.seed)
        self._base_rng = jax.random.key(seed)
        self.module = module
        module.trainer = self
        if self.guard:
            # normalize (True -> defaults) and attach the escalation/SDC
            # callback; lazy import keeps core free of resilience deps
            # when the guard is off
            from ray_lightning_tpu.resilience.guard import (
                GuardCallback,
                GuardConfig,
            )

            self.guard = GuardConfig.coerce(self.guard)
            if not any(isinstance(c, GuardCallback) for c in self.callbacks):
                self.callbacks.append(GuardCallback(self.guard))
        # mesh first: configure_model may close over it (ring attention).
        self.strategy.setup(module)
        module.setup()
        self._setup_telemetry()

        if datamodule is not None:
            datamodule.setup()
            train_dataloaders = datamodule.train_dataloader()
            val_dataloaders = val_dataloaders or datamodule.val_dataloader()
        if train_dataloaders is None:
            raise ValueError("fit() needs train_dataloaders or a datamodule")
        self.has_validation = val_dataloaders is not None
        example_batch, train_dataloaders = self._peek(train_dataloaders)

        if self.compile_cache_dir:
            # persistent cache BEFORE any step compiles: a restarted
            # worker (resilience supervisor) then deserializes every
            # program instead of recompiling it
            enable_persistent_cache(self.compile_cache_dir)
        self.tx = self._build_tx(module)
        self.state = self._init_state(module, example_batch, ckpt_path)
        self._train_step = self._make_train_step(module)
        self._eval_step = self._make_eval_step(module, module.validation_step)
        self._fit_start_perf = time.perf_counter()
        self._fit_start_step = self.global_step

        module.on_fit_start(self)
        self._invoke("on_fit_start")
        fit_error: Optional[BaseException] = None
        try:
            # warm start AFTER on_fit_start: the heartbeat sender is now
            # running, so a long AOT compile reports itself as a live
            # "compile" span instead of a silent pre-loop stall
            if self.warm_start:
                self._warm_start_train_step(example_batch)
            if self.num_sanity_val_steps and self.has_validation:
                self._run_eval_epoch(
                    val_dataloaders, limit=self.num_sanity_val_steps, sanity=True
                )
            self._fit_loop(train_dataloaders, val_dataloaders)
        except BaseException as exc:  # surface to callbacks, then re-raise
            fit_error = exc
            self._invoke("on_exception", exc)
            raise
        finally:
            # join in-flight async checkpoint writes before anything can
            # read the files or the process exits. A deferred write error
            # must not displace an in-flight training exception — but on
            # the success path it IS the failure (best_model_path must
            # never point at an unfinalized checkpoint), so re-raise.
            try:
                wait_for_checkpoints()
            except Exception:  # noqa: BLE001
                if fit_error is None:
                    raise
                log.exception("async checkpoint write failed")
            # Parity C5: the driver-side module object holds trained weights.
            if self.state is not None:
                module.params = self.state.params
            if self._profiler is not None:
                self._profiler.close()
            self._finalize_telemetry(completed=fit_error is None)
        module.on_fit_end(self)
        self._invoke("on_fit_end")
        self.is_fitted = True
        return dict(self.callback_metrics)

    def _fit_loop(self, train_loader, val_loader) -> None:
        profile_ctx = self._maybe_profile()
        with profile_ctx:
            for epoch in range(self.current_epoch, self.max_epochs):
                self.current_epoch = epoch
                if hasattr(train_loader, "set_epoch"):
                    train_loader.set_epoch(epoch)
                self.module.on_train_epoch_start(self)
                self._invoke("on_train_epoch_start")
                self._run_train_epoch(train_loader, val_loader)
                run_val = (
                    self.has_validation
                    and (epoch + 1) % self.check_val_every_n_epoch == 0
                    # mid-epoch interval may have just validated this
                    # exact step — don't run twice on identical weights
                    and self.global_step != self._last_val_step
                )
                if run_val:
                    metrics = self._run_eval_epoch(
                        val_loader, limit=self.limit_val_batches
                    )
                    self._last_val_step = self.global_step
                    self.callback_metrics.update(metrics)
                    self.module.on_validation_epoch_end(self, metrics)
                    self._invoke("on_validation_epoch_end", metrics)
                self.module.on_train_epoch_end(self)
                self._invoke("on_train_epoch_end")
                if self.should_stop or self._hit_max_steps():
                    break

    def _run_train_epoch(self, loader, val_loader=None) -> None:
        pending: Dict[str, Any] = {}
        # Mid-epoch resume: fast-forward past already-consumed batches so a
        # checkpoint saved by every_n_train_steps/val_check_interval resumes
        # the SAME epoch at the right offset (loaders reshuffle
        # deterministically per epoch via set_epoch, so offsets are stable).
        skip = self._resume_skip_batches
        self._resume_skip_batches = 0
        self._mid_epoch = True
        self._epoch_batches_done = skip
        it = iter(loader)
        for _ in range(skip):
            if next(it, None) is None:
                break
        completed = False
        # Device prefetch (pipeline/prefetch.py): cast + sharded placement
        # run up to `depth` batches ahead on a producer thread, so the
        # step's input is resident when it dispatches. The skip above
        # already advanced the raw iterator, so a mid-epoch resume never
        # pays placement for batches it will drop. Order is preserved —
        # training is bitwise-identical to the synchronous path.
        rec = self.telemetry_recorder
        t_prev: Optional[float] = None
        stream = prefetch_to_device(
            it, self._place_train_batch, depth=self.prefetch_to_device,
            recorder=rec)
        try:
            # start=skip: callbacks must see the true intra-epoch batch
            # index after a mid-epoch resume
            for batch_idx, (bs, device_batch) in enumerate(stream,
                                                           start=skip):
                if (
                    self.limit_train_batches is not None
                    # count from epoch start, not resume point, so a
                    # resumed epoch sees limit - already_consumed more
                    and self._epoch_batches_done >= self.limit_train_batches
                ):
                    # the limit DEFINES the epoch length (PTL semantics),
                    # so hitting it is epoch completion, not a mid-epoch cut
                    completed = True
                    break
                self.last_batch_size = bs
                device_batch = self._invoke_batch_start(
                    device_batch, batch_idx)
                rec.set_step(self.global_step)
                with rec.span(PH_DISPATCH, step=self.global_step):
                    self.state, metrics = self._train_step(
                        self.state, device_batch, self._base_rng
                    )
                self.global_step += 1
                self._epoch_batches_done += 1
                if rec.enabled:
                    # per-step host wall (batch boundary to batch
                    # boundary) — the measured side of the drift report
                    t_now = time.perf_counter()
                    if t_prev is not None:
                        rec.record(PH_STEP, t_prev, t_now - t_prev,
                                   step=self.global_step)
                    t_prev = t_now
                if self._profiler is not None:
                    self._profiler.on_step(self.global_step)
                pending = metrics
                # Lazy metric fetch: only sync on the logging cadence.
                if self.global_step % max(1, self.log_every_n_steps) == 0:
                    with rec.span(PH_METRICS, step=self.global_step):
                        host = _to_host(metrics)
                    self.callback_metrics.update(host)
                    pending = host
                # telemetry persistence on its own configured cadence
                # (TelemetryConfig.flush_every_n_steps): the ring drains
                # to JSONL and the goodput ledger refreshes, so a killed
                # worker leaves an almost-current account of where its
                # wall went even under a sparse logging cadence
                if (rec.enabled and self.global_step
                        % self._telemetry_flush_every == 0):
                    rec.flush()
                    self._write_telemetry_ledger(completed=False)
                self._invoke("on_train_batch_end", pending, batch_idx)
                if (self.val_check_interval and self.has_validation
                        and val_loader is not None
                        and self.global_step % self.val_check_interval == 0):
                    metrics = self._run_eval_epoch(
                        val_loader, limit=self.limit_val_batches)
                    self._last_val_step = self.global_step
                    self.callback_metrics.update(metrics)
                    self.module.on_validation_epoch_end(self, metrics)
                    self._invoke("on_validation_epoch_end", metrics)
                if self.should_stop or self._hit_max_steps():
                    break
            else:
                completed = True
        finally:
            # a mid-epoch exit of ANY kind (max_steps, early stop, a
            # preemption drain raising out of a callback) must join the
            # producer thread — never leak it holding the loader
            if isinstance(stream, DevicePrefetcher):
                stream.close()
                self.callback_metrics.update(stream.stats.to_metrics())
        if completed:
            # every batch of this epoch was consumed — subsequent saves
            # (epoch-boundary validation / on_train_epoch_end) resume at
            # the NEXT epoch
            self._mid_epoch = False
        if pending:
            self.callback_metrics.update(_to_host(pending))

    def _run_eval_epoch(
        self, loader, limit: Optional[int] = None, sanity: bool = False
    ) -> Dict[str, float]:
        """Eval totals accumulate ON DEVICE (batch-size-weighted sums of
        the replicated step metrics — each += is a tiny async dispatch, no
        transfer) and are fetched with ONE host sync at epoch end; a
        per-batch `device_get` would stall the pipeline once per batch,
        ruinous for real validation sets at 8B scale."""
        if hasattr(loader, "set_epoch"):
            loader.set_epoch(self.current_epoch)
        totals: Dict[str, Any] = {}
        weights = 0.0
        with self.telemetry_recorder.span(PH_EVAL):
            # recorder here too: an eval epoch starved on its loader
            # shows as itemized data_wait, not as opaque "eval" time
            # (the recorder credits the enclosing eval span, so the
            # buckets never double-count)
            stream = prefetch_to_device(
                loader, self._place_eval_batch,
                depth=self.prefetch_to_device,
                recorder=self.telemetry_recorder)
            try:
                for batch_idx, (bs, device_batch) in enumerate(stream):
                    if limit is not None and batch_idx >= limit:
                        break
                    metrics = self._eval_step(self.state.params,
                                              device_batch)
                    for k, v in metrics.items():
                        # accumulate in f32 — a bf16 step metric summed
                        # over hundreds of batches would round away the
                        # increments
                        scaled = jnp.asarray(v).astype(jnp.float32) * bs
                        totals[k] = (totals[k] + scaled if k in totals
                                     else scaled)
                    weights += bs
            finally:
                if isinstance(stream, DevicePrefetcher):
                    stream.close()
            if (isinstance(self._eval_step, WarmStep)
                    and self._eval_step.stats.total_s):
                self.callback_metrics.update(
                    self._eval_step.stats.to_metrics("val_"))
            if sanity or weights == 0:
                return {}
            host = _to_host(totals)
            return {k: float(v) / weights for k, v in host.items()}

    # ------------------------------------------------------- validate & co.

    def validate(self, module: Optional[TpuModule] = None, dataloaders=None,
                 datamodule: Optional[DataModule] = None) -> Dict[str, float]:
        module = self._attach(module)
        if datamodule is not None:
            datamodule.setup()
            dataloaders = datamodule.val_dataloader()
        self._eval_step = self._make_eval_step(module, module.validation_step)
        dataloaders = self._ensure_state(module, dataloaders)
        metrics = self._run_eval_epoch(dataloaders, limit=self.limit_val_batches)
        self.callback_metrics.update(metrics)
        return metrics

    def test(self, module: Optional[TpuModule] = None, dataloaders=None,
             datamodule: Optional[DataModule] = None) -> Dict[str, float]:
        module = self._attach(module)
        if datamodule is not None:
            datamodule.setup()
            dataloaders = datamodule.test_dataloader()
        self._eval_step = self._make_eval_step(module, module.test_step)
        dataloaders = self._ensure_state(module, dataloaders)
        metrics = self._run_eval_epoch(dataloaders, limit=self.limit_test_batches)
        self.callback_metrics.update(metrics)
        return metrics

    def predict(self, module: Optional[TpuModule] = None, dataloaders=None,
                datamodule: Optional[DataModule] = None) -> List[Any]:
        module = self._attach(module)
        if datamodule is not None:
            datamodule.setup()
            dataloaders = datamodule.predict_dataloader()
        dataloaders = self._ensure_state(module, dataloaders)
        step = jax.jit(lambda p, b: module.predict_step(p, b))
        outs = []
        for batch in dataloaders:
            batch = self._cast(batch)
            device_batch = self.strategy.shard_batch(batch)
            outs.append(_gather_out(step(self.state.params, device_batch)))
        return outs

    # --------------------------------------------------------- checkpoints

    def save_checkpoint(self, path: str, block: bool = True) -> str:
        assert self.state is not None, "nothing to save; fit first"
        ckpt_meta = {
            "epoch": self.current_epoch,
            "global_step": self.global_step,
            # mid-epoch saves (every_n_train_steps / val_check_interval)
            # record the batch offset so resume replays the REST of the
            # epoch instead of silently skipping it
            "mid_epoch": self._mid_epoch,
            "epoch_batch": self._epoch_batches_done,
            "module_class": type(self.module).__name__,
            "hparams": self.module.hparams,
        }
        # trainguard blessing: stamp the anomaly-free-window verdict so
        # a corruption rollback can target the last GOOD restore point
        # (latest_checkpoint(good_only=True)). Guard off => trivially
        # blessed. The counter fetch below is save-cadenced host work, a
        # rounding error next to the checkpoint write it accompanies.
        blessed = True
        if self.guard and not isinstance(
                getattr(self.state, "guard", ()), tuple):
            from ray_lightning_tpu.resilience.guard import bless_verdict

            g = jax.device_get(self.state.guard)
            upd = int(jax.device_get(self.state.step))
            blessed = bless_verdict(self.guard, g, upd)
            ckpt_meta["guard"] = {
                "skipped_steps": int(np.asarray(g.skipped)),
                "streak": int(np.asarray(g.streak)),
                "last_anomaly": int(np.asarray(g.last_anomaly)),
            }
        ckpt_meta["blessed"] = blessed
        checkpoint = {
            "params": self.state.params,
            "opt_state": self.state.opt_state,
            "step": self.state.step,
        }
        # topology provenance (docs/ELASTIC.md): stamp the writing mesh
        # and per-leaf layouts so a cross-topology restore
        # (elastic.reshard) can validate the move; checkpoints without
        # these stamps restore with NO cross-mesh validation (the
        # writing mesh is unknowable) and the elastic supervisor
        # refuses to resize onto them
        from ray_lightning_tpu.checkpoint.io import sharding_provenance

        ckpt_meta.update(
            sharding_provenance(self.strategy.mesh, checkpoint))
        self.module.on_save_checkpoint(checkpoint)
        self._invoke("on_save_checkpoint", checkpoint)
        # the span measures exactly what the TRAINING thread paid: the
        # full write when blocking, the snapshot + any join-wait on a
        # previous in-flight write when async
        with self.telemetry_recorder.span(PH_CKPT, meta={"path": path}):
            out = save_checkpoint(path, checkpoint, ckpt_meta, block=block)
        # checkpoint-overlap accounting: how long the TRAINING thread
        # stalled on checkpoint I/O (the async path's win is ~0 here)
        from ray_lightning_tpu.checkpoint.io import io_stats

        self.callback_metrics.update(io_stats())
        return out

    # ------------------------------------------------------------ plumbing

    def _attach(self, module: Optional[TpuModule]) -> TpuModule:
        module = module or self.module
        if module is None:
            raise ValueError("no module; pass one or fit first")
        self.module = module
        module.trainer = self
        if self.strategy.mesh is None:
            self.strategy.setup(module)
        else:
            # mesh already built (e.g. validate(moduleB) after
            # fit(moduleA)): rebind so param_specs/mesh come from the
            # module actually being run.
            self.strategy.bind_module(module)
        module.setup()
        return module

    def _ensure_state(self, module: TpuModule, loader):
        """Build eval-only state; returns the loader to ITERATE — when
        init peeked batch 0 off a one-shot iterator, the returned loader
        is the re-stitched chain that still contains it (callers must
        rebind, or the first batch silently disappears from eval)."""
        if self.state is not None:
            return loader
        if module.params is None:
            if loader is None:
                raise ValueError("module has no params and no data to init from")
            batch, loader = self._peek(loader)
            batch = self._cast(batch)
            rng = jax.random.key(seed_everything(self.seed))
            module.params = module.init_params(rng, batch)
        params = self.strategy.shard_params(module.params)
        step0 = jax.device_put(
            jnp.zeros((), jnp.int32), self.strategy.replicated()
        )
        self.state = TrainState(step=step0, params=params, opt_state=())
        if self._eval_step is None:
            self._eval_step = self._make_eval_step(module, module.validation_step)
        return loader

    def _build_tx(self, module: TpuModule) -> optax.GradientTransformation:
        tx = module.configure_optimizers()
        if self.gradient_clip_val:
            tx = optax.chain(optax.clip_by_global_norm(self.gradient_clip_val), tx)
        return tx

    def _init_state(
        self, module: TpuModule, example_batch, ckpt_path: Optional[str]
    ) -> TrainState:
        example_batch = self._cast(example_batch)
        # Dedicated init stream: must not collide with fold_in(rng, step=0)
        # used by the first training step.
        rng = jax.random.fold_in(self._base_rng, 0x696E6974)  # "init"

        if module.params is not None:
            # Pre-loaded weights (load_from_checkpoint / warm start).
            params = self.strategy.shard_params(module.params)
        else:
            # Shard-aware init: eval_shape → shardings → jit init with
            # out_shardings, so an 8B-param model never materializes
            # unsharded on one device.
            init_fn = lambda r: module.init_params(r, example_batch)
            abstract = jax.eval_shape(init_fn, rng)
            shardings = self.strategy.param_shardings(abstract)
            params = jax.jit(init_fn, out_shardings=shardings)(rng)

        # Optimizer state: explicitly sharded (mu/nu follow their params —
        # ZeRO semantics; scalars replicate). jit alone does NOT propagate
        # sharding here: tx.init is shape-only, so XLA drops the input
        # dependency and would leave the state on one device.
        abstract_opt = jax.eval_shape(self.tx.init, params)
        opt_shardings = self.strategy.opt_state_shardings(abstract_opt, params)
        opt_state = jax.jit(self.tx.init, out_shardings=opt_shardings)(params)
        # step is committed to the mesh (replicated) so the whole TrainState
        # lives on one device set — restored checkpoints keep that layout.
        step0 = jax.device_put(
            jnp.zeros((), jnp.int32), self.strategy.replicated()
        )
        state = TrainState(step=step0, params=params, opt_state=opt_state)
        if ckpt_path:
            meta = read_meta(ckpt_path)
            target = {"params": state.params,
                      "opt_state": state.opt_state, "step": state.step}
            move = self._reshard_move(meta)
            if move is not None:
                # cross-topology restore (docs/ELASTIC.md): the
                # checkpoint was written on a DIFFERENT mesh — validate
                # the move against its provenance and account the load
                # as a `reshard` span (goodput bucket reshard_s), so an
                # elastic shrink/grow is visible in `report`
                log.warning(
                    "resharding restore: checkpoint %s written on mesh "
                    "%s, restoring onto %s", ckpt_path,
                    move["from_mesh"], move["to_mesh"])
                with self.telemetry_recorder.span(PH_RESHARD, meta=move):
                    restored = restore_checkpoint(ckpt_path, target)
            else:
                restored = restore_checkpoint(ckpt_path, target)
            saved_epoch = int(meta.get("epoch", -1))
            if meta.get("mid_epoch", False):
                # checkpoint taken inside a partially-trained epoch:
                # resume the SAME epoch, skipping the consumed batches
                self.current_epoch = max(0, saved_epoch)
                self._resume_skip_batches = int(meta.get("epoch_batch", 0))
            else:
                self.current_epoch = saved_epoch + 1
            self.global_step = int(meta.get("global_step", 0))
            module.on_load_checkpoint(restored)
            self._invoke("on_load_checkpoint", restored)
            state = TrainState(
                step=restored["step"],
                params=restored["params"],
                opt_state=restored["opt_state"],
            )
        # outside the restore branch on purpose: a rollback that found
        # no blessed checkpoint resumes from SCRATCH and must still
        # advance past the poisoned window instead of replaying it
        self._apply_rollback_skip()
        if self.guard:
            from ray_lightning_tpu.resilience.guard import init_guard_state

            # fresh guard scalars even after a restore: the EMA re-warms
            # in warmup_steps, which beats resuming a pre-anomaly EMA
            # that no longer matches the restored loss scale
            state = state.replace(guard=jax.device_put(
                init_guard_state(), self.strategy.replicated()))
        return state

    def _reshard_move(self, meta: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """When the checkpoint's recorded writing mesh differs from the
        strategy's current mesh, validate the cross-topology move
        (elastic.reshard) and return its summary; None for a same-mesh
        restore. A provenance-carrying checkpoint whose move is ILLEGAL
        raises ReshardError here — at setup, with the leaf and axis
        named — instead of surfacing as a silent mislayout or an orbax
        shape error mid-restore.

        A LEGACY checkpoint (no ``mesh_spec`` stamp) also returns None:
        its writing mesh is unknowable, so a cross-mesh resume can
        neither be detected nor validated — the storage layer places
        the global arrays onto whatever layout this run built, and a
        warning marks the blind spot. The elastic supervisor refuses to
        RESIZE onto such a checkpoint outright (`_begin_reshard`)."""
        src = meta.get("mesh_spec")
        if not src or self.strategy.mesh is None:
            if meta and src is None and self.strategy.mesh is not None:
                log.warning(
                    "checkpoint carries no sharding provenance (written "
                    "before elastic/): restoring WITHOUT cross-mesh "
                    "validation — if the writing mesh differed from %s "
                    "this restore reshards silently; re-save once to "
                    "stamp provenance (docs/ELASTIC.md)",
                    dict(self.strategy.mesh.shape))
            return None
        cur = {str(k): int(v) for k, v in self.strategy.mesh.shape.items()}
        src = {str(k): int(v) for k, v in src.items()}
        if {k: v for k, v in src.items() if v > 1} == \
                {k: v for k, v in cur.items() if v > 1}:
            return None
        from ray_lightning_tpu.elastic.reshard import validate_reshard

        return validate_reshard(meta, cur)

    def _apply_rollback_skip(self) -> None:
        """After a trainguard rollback (resume_skip_past set by the
        supervisor from the rollback marker): the restore point is the
        last BLESSED checkpoint, behind the detection step — advance the
        data order past the poisoned window instead of replaying it.
        Also applies to a scratch resume (no blessed checkpoint found):
        the clean prefix of the epoch is sacrificed along with the
        window, which is the safe trade — suspect data is never
        retrained."""
        rsp = self.resume_skip_past
        if not rsp or int(rsp.get("detected_step", -1)) <= self.global_step:
            return  # stale marker from an older incident: resume is past it
        if int(rsp.get("epoch", -1)) != self.current_epoch:
            log.warning(
                "trainguard rollback: poisoned window spans an epoch "
                "boundary (detected epoch %s, resuming epoch %d) — "
                "replaying instead of skipping", rsp.get("epoch"),
                self.current_epoch)
            return
        target = int(rsp.get("epoch_batch", 0))
        if target > self._resume_skip_batches:
            log.warning(
                "trainguard rollback: advancing data order past the "
                "poisoned window — epoch %d resumes at batch %d "
                "(instead of %d)", self.current_epoch, target,
                self._resume_skip_batches)
            self._resume_skip_batches = target

    def _make_train_step(self, module: TpuModule):
        tx = self.tx
        accum = self.accumulate_grad_batches
        guard_cfg = self.guard if (self.guard and self.guard.enabled) \
            else None
        if guard_cfg is not None:
            from ray_lightning_tpu.resilience.guard import apply_guard

        def loss_fn(params, batch, rng):
            out = module.training_step(params, batch, rng)
            if isinstance(out, tuple):
                loss, metrics = out
            else:
                loss, metrics = out, {}
            metrics = {**metrics, **module.pop_logged()}
            return loss, metrics

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def step(state: TrainState, batch, base_rng):
            rng = jax.random.fold_in(base_rng, state.step)
            if accum == 1:
                (loss, metrics), grads = grad_fn(state.params, batch, rng)
            else:
                # batch leading axis = accum microbatches; scan-accumulate.
                def body(carry, micro):
                    sum_grads, i = carry
                    (l, m), g = grad_fn(
                        state.params, micro, jax.random.fold_in(rng, i)
                    )
                    sum_grads = jax.tree.map(jnp.add, sum_grads, g)
                    return (sum_grads, i + 1), (l, m)

                zero = jax.tree.map(jnp.zeros_like, state.params)
                (grads, _), (losses, metricses) = jax.lax.scan(
                    body, (zero, 0), batch
                )
                grads = jax.tree.map(lambda g: g / accum, grads)
                loss = losses.mean()
                metrics = jax.tree.map(lambda m: m.mean(axis=0), metricses)
            updates, opt_state = tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            grad_norm = optax.global_norm(grads)
            metrics = {"loss": loss, "grad_norm": grad_norm, **metrics}
            if guard_cfg is not None:
                # trainguard tier 1 (resilience/guard.py): an anomalous
                # update (non-finite loss/grad or a loss spike vs the
                # EMA) is discarded by a tree-select — params/opt-state/
                # step pass through unchanged; the flag and counters are
                # ordinary metric scalars riding the existing lazy fetch
                params, opt_state, new_step, gstate, gmetrics = \
                    apply_guard(guard_cfg, state.guard, state.step, loss,
                                grad_norm, params, state.params,
                                opt_state, state.opt_state)
                return (
                    state.replace(step=new_step, params=params,
                                  opt_state=opt_state, guard=gstate),
                    {**metrics, **gmetrics},
                )
            return (
                state.replace(
                    step=state.step + 1, params=params, opt_state=opt_state
                ),
                metrics,
            )

        # check_args=(1,): only the batch can drift — re-checking the
        # whole TrainState per step would put O(param leaves) host work
        # back on the hot path
        return WarmStep(jax.jit(step, donate_argnums=(0,)),
                        label="train_step", check_args=(1,),
                        recorder=self.telemetry_recorder)

    def _make_eval_step(self, module: TpuModule, step_fn):
        def step(params, batch):
            metrics = step_fn(params, batch)
            logged = module.pop_logged()
            if metrics is None:
                metrics = {}
            if not isinstance(metrics, dict):
                metrics = {"val_loss": metrics}
            return {**metrics, **logged}

        # auto: the eval batch shape is unknown until validation runs, so
        # the AOT compile happens on the first eval batch (still recorded
        # as a first-class metric, val_compile_time_s)
        return WarmStep(jax.jit(step), label="eval_step",
                        auto=self.warm_start, check_args=(1,),
                        recorder=self.telemetry_recorder)

    def _warm_start_train_step(self, example_batch) -> None:
        """AOT lower().compile() the train step for the known shapes —
        the cold compile happens HERE, visible as compile_time_s, instead
        of hiding inside the first batch. With a persistent cache
        (compile_cache_dir / the supervisor's per-plan dir) a restarted
        process deserializes instead of recompiling, so this reads ~zero
        on every warm start after the first."""
        _, device_batch = self._place_train_batch(example_batch)
        stats = self._train_step.warm(self.state, device_batch,
                                      self._base_rng)
        self.callback_metrics.update(stats.to_metrics())

    def _place_train_batch(self, batch):
        """Host batch -> (leading dim, device-resident batch); the
        prefetcher's producer stage (runs on its thread)."""
        batch = self._cast(batch)
        return _leading_dim(batch), self._shard_train_batch(batch)

    def _place_eval_batch(self, batch):
        batch = self._cast(batch)
        return _leading_dim(batch) or 1, self.strategy.shard_batch(batch)

    def _shard_train_batch(self, batch):
        accum = self.accumulate_grad_batches
        if accum > 1:
            def split(x):
                x = np.asarray(x)
                if x.shape[0] % accum != 0:
                    raise ValueError(
                        f"batch dim {x.shape[0]} not divisible by "
                        f"accumulate_grad_batches={accum}"
                    )
                return x.reshape((accum, x.shape[0] // accum) + x.shape[1:])

            batch = jax.tree.map(split, batch)
            import jax.sharding as js

            spec = self.strategy.batch_spec()
            micro_spec = js.PartitionSpec(None, *spec)
            sharding = js.NamedSharding(self.strategy.mesh, micro_spec)
            return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)
        return self.strategy.shard_batch(batch)

    def _cast(self, batch):
        if self.precision != "bf16":
            return batch
        def cast(x):
            x = np.asarray(x)
            if np.issubdtype(x.dtype, np.floating):
                return x.astype(jnp.bfloat16)
            return x
        return jax.tree.map(cast, batch)

    def _peek(self, loader):
        """Grab batch 0 without losing it. One-shot iterators (generators)
        are re-stitched with itertools.chain; they support one epoch only."""
        import itertools

        it = iter(loader)
        try:
            first = next(it)
        except StopIteration:
            # an empty loader would otherwise surface as a raw
            # StopIteration; the usual cause is drop_last truncation —
            # a per-process shard smaller than one batch
            raise ValueError(
                "the dataloader yielded no batches. With drop_last=True "
                "(the static-shape default) this happens when a shard "
                "holds fewer rows than batch_size — e.g. a small dataset "
                "split over many processes. Lower batch_size or grow the "
                "dataset."
            ) from None
        if it is loader:
            if self.max_epochs > 1:
                log.warning(
                    "train data is a one-shot iterator; it will be exhausted "
                    "after one epoch — pass a re-iterable (e.g. DataLoader) "
                    "for multi-epoch training"
                )
            return first, itertools.chain([first], it)
        return first, loader

    def _hit_max_steps(self) -> bool:
        return self.max_steps > 0 and self.global_step >= self.max_steps

    def _invoke(self, hook: str, *args) -> None:
        for cb in self.callbacks:
            getattr(cb, hook)(self, self.module, *args)

    def _invoke_batch_start(self, batch, batch_idx: int):
        """on_train_batch_start with batch replacement: a callback that
        returns a non-None value substitutes the device batch (the
        fault injector's nan_loss/grad_blowup poisoning rides this).
        Host-side per-batch dispatch only — no device sync."""
        for cb in self.callbacks:
            out = cb.on_train_batch_start(self, self.module, batch,
                                          batch_idx)
            if out is not None:
                batch = out
        return batch

    def _maybe_profile(self):
        if not self.profiler_dir:
            return contextlib.nullcontext()
        os.makedirs(self.profiler_dir, exist_ok=True)
        return _ProfilerCtx(self.profiler_dir)

    # ------------------------------------------------------------ telemetry

    def _setup_telemetry(self) -> None:
        """Build the span recorder + profiler controller for this fit.
        Host bookkeeping only — nothing here reaches the jitted step, so
        telemetry=off vs on compile the byte-identical program."""
        self.telemetry = TelemetryConfig.coerce(self.telemetry)
        rank = jax.process_index()
        if self.telemetry is not None:
            self.telemetry_recorder = TelemetryRecorder(
                directory=self.telemetry.resolved_dir(
                    self.default_root_dir),
                rank=rank, ring_size=self.telemetry.ring_size)
            self._telemetry_flush_every = max(
                1, self.telemetry.flush_every_n_steps)
            self._launch_s = _launch_seconds()
        self.profile = ProfileConfig.coerce(self.profile)
        if self.profile is not None:
            self._profiler = ProfilerController(self.profile, rank=rank)

    def _write_telemetry_ledger(self, completed: bool) -> None:
        """Refresh this rank's goodput ledger (telemetry/goodput.py) —
        cadenced AND final, atomic replace, so a SIGKILLed attempt still
        leaves an almost-current account for the driver to assemble."""
        rec = self.telemetry_recorder
        if not rec.enabled or rec.directory is None \
                or self._fit_start_perf is None:
            return
        ledger = _goodput.worker_ledger(
            rec, time.perf_counter() - self._fit_start_perf,
            rank=rec.rank, start_step=self._fit_start_step,
            end_step=self.global_step, launch_s=self._launch_s,
            completed=completed)
        _goodput.write_ledger(rec.directory, ledger, uid=rec.uid)

    def _finalize_telemetry(self, completed: bool) -> None:
        rec = self.telemetry_recorder
        if not rec.enabled:
            return
        totals = rec.phase_totals()
        wall = (time.perf_counter() - self._fit_start_perf
                if self._fit_start_perf is not None else 0.0)
        stalls = sum(totals.get(p, 0.0) for p in
                     ("compile", "data_wait", "ckpt_stall", "eval",
                      "metrics_fetch"))
        self.callback_metrics.update({
            "telemetry_compile_s": totals.get("compile", 0.0),
            "telemetry_data_wait_s": totals.get("data_wait", 0.0),
            "telemetry_ckpt_stall_s": totals.get("ckpt_stall", 0.0),
            "telemetry_eval_s": totals.get("eval", 0.0),
            "telemetry_spans_dropped": float(rec.dropped),
            "goodput_fraction": (max(0.0, wall - stalls) / wall
                                 if wall > 0 else 0.0),
        })
        self._write_telemetry_ledger(completed=completed)
        rec.close()


def _launch_seconds() -> float:
    """Worker spawn -> fit start (imports, jax init, rendezvous) — the
    goodput launch bucket. Zero outside a runtime worker: a local fit
    has no spawn cost worth charging."""
    try:
        from ray_lightning_tpu.runtime import session

        s = session.get_session()
        started = getattr(s, "started_at", None) if s is not None else None
        if started:
            return max(0.0, time.time() - started)
    except Exception:  # noqa: BLE001 — accounting must never fail a fit
        pass
    return 0.0


class _ProfilerCtx:
    """jax.profiler trace over the fit loop (SURVEY §5.1: absent in the
    reference; table stakes on TPU — produces XPlane traces per host)."""

    def __init__(self, logdir: str):
        self.logdir = logdir

    def __enter__(self):
        jax.profiler.start_trace(self.logdir)
        return self

    def __exit__(self, *exc):
        jax.profiler.stop_trace()
        return False


def _gather_out(tree) -> Any:
    """Host copy of a possibly-multi-process prediction output: batch-axis-
    sharded arrays are not fully addressable on any one process, so gather
    globally first (every rank sees the full output; rank 0's is the
    conventional carrier through run_distributed)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        tree = multihost_utils.process_allgather(tree, tiled=True)
        return jax.tree.map(np.asarray, tree)
    return _to_host(tree)


def _to_host(tree) -> Any:
    fetched = jax.device_get(tree)
    if isinstance(fetched, dict):
        return {
            k: (np.asarray(v) if hasattr(v, "shape") and np.ndim(v) else float(v))
            for k, v in fetched.items()
        }
    return jax.tree.map(np.asarray, fetched)


def _leading_dim(batch) -> Optional[int]:
    leaves = jax.tree.leaves(batch)
    if not leaves:
        return None
    shape = getattr(leaves[0], "shape", None)
    return int(shape[0]) if shape else None
