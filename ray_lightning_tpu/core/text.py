"""Text/LM data preparation: chunking and sequence packing.

Static-shape-first (XLA compiles one step per shape): both helpers emit
fixed-[N, seq_len+1] token matrices ready for the Llama family's
{"tokens", "mask"} batch format (models/llama.py `_split`), where
column i is the input and column i+1 its target. All hot paths are
numpy-vectorized (stride tricks + concatenate) — no per-token Python
loops, so corpus-scale inputs stay 4 bytes/token.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence

import numpy as np


def _rows_from_stream(t: np.ndarray, seq_len: int, pad_id: int,
                      drop_last: bool) -> Dict[str, np.ndarray]:
    """Shared chunker: [len] stream -> {"tokens": [N, S+1], "mask"?}.

    Rows stride by S (one-token overlap carries the boundary target).
    A padded tail row (drop_last=False) comes with a target mask; full
    rows need none, so "mask" is only emitted when padding exists.
    """
    stride = seq_len
    n = max(0, (len(t) - 1) // stride)  # empty stream must not underflow
    rows = []
    if n >= 1:
        windows = np.lib.stride_tricks.sliding_window_view(t, seq_len + 1)
        rows.append(np.ascontiguousarray(windows[::stride][:n]))
    tail_len = len(t) - n * stride  # includes the overlap token
    has_tail = not drop_last and tail_len > 1
    if has_tail:
        tail = t[n * stride:]
        pad = np.full(seq_len + 1 - len(tail), pad_id, np.int32)
        rows.append(np.concatenate([tail, pad])[None])
    if not rows:
        raise ValueError(
            f"stream of {len(t)} tokens cannot fill a row of "
            f"seq_len+1={seq_len + 1}"
            + ("" if drop_last else " (need at least 2 tokens)")
        )
    tokens = np.concatenate(rows) if len(rows) > 1 else rows[0]
    out = {"tokens": tokens}
    if has_tail:
        mask = np.ones((len(tokens), seq_len), np.float32)
        mask[-1] = 0.0
        mask[-1, : tail_len - 1] = 1.0
        out["mask"] = mask
    return out


def chunk_tokens(flat_tokens, seq_len: int, drop_last: bool = True,
                 pad_id: int = 0) -> Dict[str, np.ndarray]:
    """Split one long token stream into [N, seq_len+1] training rows.

    With ``drop_last=False`` the padded tail row is kept and a target
    ``mask`` is emitted so padding never contributes loss.
    """
    t = np.asarray(flat_tokens, dtype=np.int32).reshape(-1)
    return _rows_from_stream(t, seq_len, pad_id, drop_last)


def pack_sequences(
    docs: Iterable[Sequence[int]],
    seq_len: int,
    pad_id: int = 0,
    eos_id: Optional[int] = None,
    drop_last: bool = True,
) -> Dict[str, np.ndarray]:
    """Greedily pack variable-length documents into fixed rows.

    Documents are laid end-to-end (an ``eos_id`` separator appended to
    each when given). A document longer than a row simply continues into
    the next row (stream semantics) — nothing is truncated. Output
    follows `_rows_from_stream` ({"tokens"} + "mask" iff a padded tail
    row exists).
    """
    parts = []
    eos = (np.asarray([eos_id], np.int32) if eos_id is not None else None)
    for doc in docs:
        parts.append(np.asarray(doc, dtype=np.int32).reshape(-1))
        if eos is not None:
            parts.append(eos)
    t = np.concatenate(parts) if parts else np.zeros(0, np.int32)
    return _rows_from_stream(t, seq_len, pad_id, drop_last)


def tokenize_and_pack(
    texts: Iterable[str],
    tokenizer,
    seq_len: int,
    add_eos: bool = True,
    drop_last: bool = True,
) -> Dict[str, np.ndarray]:
    """Convenience over any HF-style tokenizer (``tokenizer.encode`` +
    ``eos_token_id``/``pad_token_id`` attributes)."""
    eos = getattr(tokenizer, "eos_token_id", None) if add_eos else None
    pad = getattr(tokenizer, "pad_token_id", None)
    docs = (tokenizer.encode(t) for t in texts)
    return pack_sequences(docs, seq_len, pad_id=pad if pad is not None else 0,
                          eos_id=eos, drop_last=drop_last)
