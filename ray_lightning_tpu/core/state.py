"""Training state pytree: the single donated argument of the jitted step."""
from __future__ import annotations

from typing import Any

import flax.struct
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    """Everything that evolves across steps, as one pytree.

    The whole state is donated to the jitted train step so XLA updates
    params/opt-state in place in HBM (no copy per step).
    """

    step: jnp.ndarray  # scalar int32
    params: Any
    opt_state: Any
    #: trainguard slice (resilience/guard.py GuardState) — a handful of
    #: replicated scalars carrying the loss EMA + anomaly counters
    #: through the jitted step. The empty-tuple default contributes no
    #: pytree leaves, so unguarded training compiles the exact same
    #: program as before the guard existed.
    guard: Any = ()

    @classmethod
    def create(cls, params: Any, tx: optax.GradientTransformation) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
        )
