// rlt_batcher — threaded host-side batch assembly with prefetch.
//
// The TPU-native stand-in for the data-path work the reference delegated
// to Ray's C++ core (plasma object transport feeding torch DataLoader
// workers; reference ray_lightning/ray_ddp.py ships whole datasets through
// ray.put). Here the hot host-side op is "gather N shuffled rows into a
// contiguous batch buffer" — done by a worker pool one-or-more batches
// AHEAD of the training loop, so batch assembly overlaps device compute
// instead of serializing with it.
//
// Model: a ring of `depth` slots, each holding one assembled batch for
// every array in the dataset pytree. Worker threads claim batch indices,
// gather rows (memcpy per row; rows are contiguous because arrays are
// C-order with the batch dim leading), and publish READY slots. The
// consumer takes batches strictly in order (static shapes; deterministic
// iteration), and releases each slot once the batch is on device.
//
// Plain C ABI (ctypes-friendly, no pybind11 dependency).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

enum class SlotState { kFree, kFilling, kReady, kInUse };

struct Slot {
  std::vector<std::vector<uint8_t>> buffers;  // one per array
  int64_t batch_index = -1;
  int64_t rows = 0;
  SlotState state = SlotState::kFree;
};

struct Loader {
  // dataset
  int n_arrays = 0;
  std::vector<const uint8_t*> data;
  std::vector<int64_t> row_bytes;
  int64_t n_rows = 0;
  int64_t batch_size = 0;
  bool drop_last = true;

  // epoch state
  std::vector<int64_t> order;
  int64_t n_batches = 0;
  int64_t next_fill = 0;   // next batch index a worker should claim
  int64_t next_serve = 0;  // next batch index the consumer receives

  // machinery
  std::vector<Slot> slots;
  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv;  // all waiting (workers + consumer)
  bool stopping = false;

  ~Loader() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stopping = true;
    }
    cv.notify_all();
    for (auto& t : workers) t.join();
  }
};

int64_t batch_rows(const Loader& L, int64_t b) {
  int64_t start = b * L.batch_size;
  int64_t n = static_cast<int64_t>(L.order.size());
  return std::min(L.batch_size, n - start);
}

void fill_slot(Loader& L, Slot& slot, int64_t b) {
  const int64_t rows = batch_rows(L, b);
  const int64_t* idx = L.order.data() + b * L.batch_size;
  for (int a = 0; a < L.n_arrays; ++a) {
    const int64_t rb = L.row_bytes[a];
    uint8_t* dst = slot.buffers[a].data();
    const uint8_t* src = L.data[a];
    for (int64_t r = 0; r < rows; ++r) {
      std::memcpy(dst + r * rb, src + idx[r] * rb, rb);
    }
  }
  slot.rows = rows;
  slot.batch_index = b;
}

void worker_main(Loader* L) {
  std::unique_lock<std::mutex> lk(L->mu);
  while (true) {
    Slot* slot = nullptr;
    int64_t b = -1;
    L->cv.wait(lk, [&] {
      if (L->stopping) return true;
      if (L->next_fill >= L->n_batches) return false;  // epoch drained
      for (auto& s : L->slots) {
        if (s.state == SlotState::kFree) return true;
      }
      return false;
    });
    if (L->stopping) return;
    for (auto& s : L->slots) {
      if (s.state == SlotState::kFree) {
        slot = &s;
        break;
      }
    }
    b = L->next_fill++;
    slot->state = SlotState::kFilling;
    lk.unlock();
    fill_slot(*L, *slot, b);
    lk.lock();
    slot->state = SlotState::kReady;
    L->cv.notify_all();
  }
}

}  // namespace

extern "C" {

void* rlt_loader_create(int n_arrays, const void** data,
                        const int64_t* row_bytes, int64_t n_rows,
                        int64_t batch_size, int drop_last, int depth,
                        int n_threads) {
  if (n_arrays <= 0 || n_rows <= 0 || batch_size <= 0) return nullptr;
  auto* L = new Loader();
  L->n_arrays = n_arrays;
  L->n_rows = n_rows;
  L->batch_size = batch_size;
  L->drop_last = drop_last != 0;
  for (int a = 0; a < n_arrays; ++a) {
    L->data.push_back(static_cast<const uint8_t*>(data[a]));
    L->row_bytes.push_back(row_bytes[a]);
  }
  depth = depth < 2 ? 2 : depth;
  L->slots.resize(depth);
  for (auto& s : L->slots) {
    s.buffers.resize(n_arrays);
    for (int a = 0; a < n_arrays; ++a) {
      s.buffers[a].resize(batch_size * L->row_bytes[a]);
    }
  }
  n_threads = n_threads < 1 ? 1 : n_threads;
  for (int t = 0; t < n_threads; ++t) {
    L->workers.emplace_back(worker_main, L);
  }
  return L;
}

// Begin an epoch. `order` is the (possibly shuffled, possibly sharded)
// row-index sequence for this epoch. Safe to call with the previous
// epoch only partially consumed (the trainer breaks out of iteration on
// limit_train_batches / max_steps / early stop): new claims are fenced
// off first, then in-flight fills are drained before `order` and the
// slot states are touched — fill_slot reads/writes outside the mutex.
void rlt_loader_set_epoch(void* handle, const int64_t* order, int64_t n) {
  auto* L = static_cast<Loader*>(handle);
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->n_batches = 0;  // no worker can claim a new batch past this point
    L->cv.wait(lk, [&] {
      for (auto& s : L->slots) {
        if (s.state == SlotState::kFilling) return false;
      }
      return true;
    });
    L->order.assign(order, order + n);
    L->n_batches = L->drop_last ? n / L->batch_size
                                : (n + L->batch_size - 1) / L->batch_size;
    L->next_fill = 0;
    L->next_serve = 0;
    for (auto& s : L->slots) {
      s.state = SlotState::kFree;
      s.batch_index = -1;
    }
  }
  L->cv.notify_all();
}

// Blocks until the next in-order batch is assembled. Fills `out_ptrs`
// (one pointer per array) and `out_rows`. Returns the slot id to pass to
// rlt_loader_release, or -1 at end of epoch.
int rlt_loader_next(void* handle, void** out_ptrs, int64_t* out_rows) {
  auto* L = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(L->mu);
  if (L->next_serve >= L->n_batches) return -1;
  const int64_t want = L->next_serve;
  Slot* slot = nullptr;
  L->cv.wait(lk, [&] {
    if (L->stopping) return true;
    for (auto& s : L->slots) {
      if (s.state == SlotState::kReady && s.batch_index == want) {
        slot = &s;
        return true;
      }
    }
    return false;
  });
  if (L->stopping || slot == nullptr) return -1;
  slot->state = SlotState::kInUse;
  L->next_serve++;
  for (int a = 0; a < L->n_arrays; ++a) {
    out_ptrs[a] = slot->buffers[a].data();
  }
  *out_rows = slot->rows;
  return static_cast<int>(slot - L->slots.data());
}

void rlt_loader_release(void* handle, int slot_id) {
  auto* L = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    if (slot_id >= 0 && slot_id < static_cast<int>(L->slots.size())) {
      L->slots[slot_id].state = SlotState::kFree;
      L->slots[slot_id].batch_index = -1;
    }
  }
  L->cv.notify_all();
}

void rlt_loader_destroy(void* handle) {
  delete static_cast<Loader*>(handle);
}

}  // extern "C"
