"""Native (C++) runtime components + ctypes bindings.

The reference's data/object path lived in Ray's C++ core (plasma store,
raylet); the rebuild's native layer starts here with the host-side batch
assembler (batcher.cpp): a worker pool gathers shuffled rows into
contiguous batch buffers one-or-more batches ahead of the training loop,
overlapping input assembly with device compute.

Built on demand with the system toolchain (g++ -O3 -shared); no
pybind11 — plain C ABI over ctypes. Everything degrades gracefully: if
the toolchain or the build is unavailable, callers fall back to the pure
numpy path.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional

import numpy as np

from ray_lightning_tpu.analysis.lockwatch import san_lock
from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "batcher.cpp")
_BUILD_DIR = os.path.join(_HERE, "build")
_LIB_PATH = os.path.join(_BUILD_DIR, "librlt_batcher.so")

_lib_lock = san_lock("native.lib")
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _compile() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # Build to a private temp path, then atomically rename into place:
    # many worker processes may race to build (sweep trials, SPMD hosts),
    # and dlopen of a half-written .so must be impossible.
    tmp = f"{_LIB_PATH}.tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", tmp]
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=120)
    except (OSError, subprocess.TimeoutExpired) as exc:
        log.warning("native batcher build unavailable: %s", exc)
        return False
    if out.returncode != 0:
        log.warning("native batcher build failed:\n%s", out.stderr[-2000:])
        return False
    try:
        os.replace(tmp, _LIB_PATH)
    except OSError as exc:
        log.warning("native batcher install failed: %s", exc)
        return False
    return True


def load_library() -> Optional[ctypes.CDLL]:
    """Build (if stale) and dlopen the native library; None on failure."""
    global _lib, _lib_failed
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            stale = (not os.path.exists(_LIB_PATH)
                     or os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC))
            # Once-only init lock, deliberately held through the build:
            # the first caller compiles while every other caller WANTS
            # to wait rather than dlopen a torn .so.
            if stale and not _compile():  # rlt: disable=RLT705
                _lib_failed = True
                return None
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as exc:
            log.warning("native batcher load failed: %s", exc)
            _lib_failed = True
            return None
        lib.rlt_loader_create.restype = ctypes.c_void_p
        lib.rlt_loader_create.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.rlt_loader_set_epoch.restype = None
        lib.rlt_loader_set_epoch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
        lib.rlt_loader_next.restype = ctypes.c_int
        lib.rlt_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64)]
        lib.rlt_loader_release.restype = None
        lib.rlt_loader_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.rlt_loader_destroy.restype = None
        lib.rlt_loader_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return load_library() is not None


class NativeBatcher:
    """Prefetching batch iterator over a flat dict of numpy arrays.

    Yields dicts of numpy arrays shaped like the python loader's batches.
    By default each yielded batch is a copy (safe to hold indefinitely);
    `zero_copy=True` yields views into the slot buffer that are only
    valid until the next batch is requested — the right mode when the
    consumer immediately `device_put`s (the Trainer's pattern).
    """

    def __init__(self, data: Dict[str, np.ndarray], batch_size: int,
                 drop_last: bool = True, depth: int = 3,
                 n_threads: int = 2, zero_copy: bool = False):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native batcher unavailable")
        self._lib = lib
        self.keys: List[str] = list(data.keys())
        self.arrays = [np.ascontiguousarray(data[k]) for k in self.keys]
        n = len(self.arrays[0])
        for a in self.arrays:
            if len(a) != n:
                raise ValueError("all arrays must share the leading dim")
        self.n_rows = n
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.zero_copy = zero_copy
        self._row_shapes = [a.shape[1:] for a in self.arrays]
        self._dtypes = [a.dtype for a in self.arrays]
        row_bytes = (ctypes.c_int64 * len(self.arrays))(
            *[a.strides[0] if a.ndim > 1 else a.itemsize
              for a in self.arrays])
        ptrs = (ctypes.c_void_p * len(self.arrays))(
            *[a.ctypes.data_as(ctypes.c_void_p).value for a in self.arrays])
        self._handle = lib.rlt_loader_create(
            len(self.arrays), ptrs, row_bytes, n, batch_size,
            int(drop_last), depth, n_threads,
        )
        if not self._handle:
            raise RuntimeError("rlt_loader_create failed")
        self._pending_slot = -1

    def set_epoch(self, order: np.ndarray) -> None:
        order = np.ascontiguousarray(order, dtype=np.int64)
        self._order = order  # keep alive during the C call
        self._lib.rlt_loader_set_epoch(
            self._handle, order.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(order),
        )
        self._pending_slot = -1

    def __iter__(self):
        out_ptrs = (ctypes.c_void_p * len(self.arrays))()
        out_rows = ctypes.c_int64()
        while True:
            if self._pending_slot >= 0:
                self._lib.rlt_loader_release(self._handle, self._pending_slot)
                self._pending_slot = -1
            slot = self._lib.rlt_loader_next(self._handle, out_ptrs,
                                             ctypes.byref(out_rows))
            if slot < 0:
                return
            rows = out_rows.value
            batch = {}
            for i, key in enumerate(self.keys):
                shape = (rows,) + self._row_shapes[i]
                count = int(np.prod(shape))
                buf = (ctypes.c_char * (count * self._dtypes[i].itemsize)
                       ).from_address(out_ptrs[i])
                arr = np.frombuffer(buf, dtype=self._dtypes[i],
                                    count=count).reshape(shape)
                batch[key] = arr if self.zero_copy else arr.copy()
            if self.zero_copy:
                self._pending_slot = slot  # released on the next pull
                yield batch
            else:
                self._lib.rlt_loader_release(self._handle, slot)
                yield batch

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.rlt_loader_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter-teardown best effort
            pass


__all__ = ["NativeBatcher", "available", "load_library"]
