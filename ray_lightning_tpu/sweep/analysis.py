"""Experiment results: per-trial records + best-of queries.

Rebuild of the surface the reference's tests consume from Ray Tune's
``ExperimentAnalysis`` — ``analysis.best_config`` and
``analysis.best_checkpoint`` (reference tests/test_tune.py:44-45,60-74),
trial dataframes (reference examples/ray_ddp_example.py:114).
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional


class Trial:
    PENDING = "pending"
    RUNNING = "running"
    DONE = "done"
    STOPPED = "stopped"   # early-stopped by the scheduler
    ERROR = "error"

    def __init__(self, trial_id: str, config: Dict[str, Any], trial_dir: str,
                 resources=None):
        self.trial_id = trial_id
        self.config = config
        self.trial_dir = trial_dir
        self.resources = resources
        self.status = Trial.PENDING
        self.history: List[Dict[str, Any]] = []
        self.last_result: Dict[str, Any] = {}
        self.checkpoints: List[str] = []   # registered paths, append order
        self.error: Optional[str] = None
        self.result: Any = None            # trainable's return value
        self.restarts = 0                  # trial-level retries performed
        #                                    (sweep retry_policy; resumes
        #                                    from last_checkpoint)

    @property
    def iterations(self) -> int:
        return len(self.history)

    @property
    def last_checkpoint(self) -> Optional[str]:
        return self.checkpoints[-1] if self.checkpoints else None

    def metric_value(self, metric: str, mode: str = "min",
                     scope: str = "last") -> Optional[float]:
        if scope == "last":
            v = self.last_result.get(metric)
            return float(v) if v is not None else None
        vals = [float(h[metric]) for h in self.history if metric in h]
        if not vals:
            return None
        return min(vals) if mode == "min" else max(vals)

    def __repr__(self) -> str:
        return (f"Trial({self.trial_id}, status={self.status}, "
                f"iters={self.iterations}, config={self.config})")


class ExperimentAnalysis:
    def __init__(self, trials: List[Trial], metric: Optional[str],
                 mode: str = "min"):
        self.trials = trials
        self.default_metric = metric
        self.default_mode = mode

    # ----------------------------------------------------------- queries
    def _pick(self, metric: Optional[str], mode: Optional[str],
              scope: str) -> Optional[Trial]:
        metric = metric or self.default_metric
        mode = mode or self.default_mode
        if metric is None:
            raise ValueError("no metric given and no sweep-level default")
        best: Optional[Trial] = None
        best_v = math.inf
        sign = 1.0 if mode == "min" else -1.0
        for t in self.trials:
            if t.status == Trial.ERROR:
                continue
            v = t.metric_value(metric, mode, scope)
            if v is None or math.isnan(v):
                continue
            if sign * v < best_v:
                best_v = sign * v
                best = t
        return best

    def get_best_trial(self, metric: Optional[str] = None,
                       mode: Optional[str] = None,
                       scope: str = "last") -> Optional[Trial]:
        return self._pick(metric, mode, scope)

    @property
    def best_trial(self) -> Optional[Trial]:
        return self.get_best_trial()

    @property
    def best_config(self) -> Optional[Dict[str, Any]]:
        t = self.get_best_trial()
        return t.config if t else None

    @property
    def best_checkpoint(self) -> Optional[str]:
        t = self.get_best_trial()
        return t.last_checkpoint if t else None

    @property
    def results(self) -> Dict[str, Dict[str, Any]]:
        return {t.trial_id: t.last_result for t in self.trials}

    def dataframe(self) -> List[Dict[str, Any]]:
        """One flat record per trial (a list of dicts rather than a hard
        pandas dependency; ``pandas.DataFrame(analysis.dataframe())`` works
        verbatim if pandas is available)."""
        rows = []
        for t in self.trials:
            row = {"trial_id": t.trial_id, "status": t.status,
                   "iterations": t.iterations,
                   "checkpoint": t.last_checkpoint}
            row.update({f"config/{k}": v for k, v in t.config.items()})
            row.update(t.last_result)
            rows.append(row)
        return rows

    def errors(self) -> Dict[str, str]:
        return {t.trial_id: t.error for t in self.trials
                if t.status == Trial.ERROR}
