"""Trial-side session: the report channel from a trial back to the sweep.

The reference's report path was one-way and trampoline-shaped: worker
rank 0 enqueued ``lambda: tune.report(...)`` (reference tune.py:97-101),
the trial driver executed it (reference util.py:88-93), and Ray Tune's
session carried it to the sweep scheduler; a scheduler decision to stop
a trial was delivered by killing the trial actor.

The rebuild makes the channel **duplex**: ``report()`` sends the metrics
to the sweep driver and *blocks for the scheduler's verdict* on the same
connection. A ``stop`` verdict raises :class:`TrialStopped` inside the
trial process, unwinding the fit loop (and any nested worker group)
cooperatively — no actor kill needed, and the trial's device group is
released deterministically.
"""
from __future__ import annotations

from multiprocessing.connection import Client
from typing import Any, Callable, Dict, Optional


class TrialStopped(BaseException):
    """Raised inside a trial when the scheduler says stop. Subclasses
    BaseException (like KeyboardInterrupt) so ordinary ``except Exception``
    blocks in user training code don't swallow the stop."""


class TrialContext:
    """Bound once per trial process; ``report`` is the only required op."""

    trial_id: str
    trial_dir: str
    #: checkpoint to resume from (set when the sweep restarts an
    #: interrupted trial; read via get_checkpoint())
    last_checkpoint: Optional[str] = None

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[str] = None) -> None:
        raise NotImplementedError

    def close(self) -> None:  # noqa: B027
        pass


class RemoteTrialContext(TrialContext):
    """Trial in its own process: reports ride a dedicated authenticated
    socket back to the sweep driver (lazy-connected on first report)."""

    def __init__(self, trial_id: str, trial_dir: str,
                 address: tuple, authkey: bytes,
                 last_checkpoint: Optional[str] = None):
        self.trial_id = trial_id
        self.trial_dir = trial_dir
        self.last_checkpoint = last_checkpoint
        self._address = address
        self._authkey = authkey
        self._conn = None

    def _connect(self):
        if self._conn is None:
            self._conn = Client(tuple(self._address), authkey=self._authkey)
            self._conn.send(("hello", self.trial_id))
        return self._conn

    def report(self, metrics, checkpoint=None) -> None:
        conn = self._connect()
        conn.send(("report", self.trial_id, dict(metrics), checkpoint))
        verdict = conn.recv()
        if verdict == "stop":
            raise TrialStopped(self.trial_id)

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.send(("bye", self.trial_id))
                self._conn.close()
            except OSError:
                pass
            self._conn = None


class LocalTrialContext(TrialContext):
    """Inline executor: report goes straight into the runner (same
    process); a stop verdict raises immediately."""

    def __init__(self, trial_id: str, trial_dir: str,
                 report_fn: Callable[[str, Dict[str, Any], Optional[str]], str],
                 last_checkpoint: Optional[str] = None):
        self.trial_id = trial_id
        self.trial_dir = trial_dir
        self.last_checkpoint = last_checkpoint
        self._report_fn = report_fn

    def report(self, metrics, checkpoint=None) -> None:
        verdict = self._report_fn(self.trial_id, dict(metrics), checkpoint)
        if verdict == "stop":
            raise TrialStopped(self.trial_id)


_ctx: Optional[TrialContext] = None


def init_trial_session(ctx: TrialContext) -> None:
    global _ctx
    _ctx = ctx


def reset_trial_session() -> None:
    global _ctx
    _ctx = None


def get_trial_session() -> Optional[TrialContext]:
    return _ctx


def is_trial_session_enabled() -> bool:
    """True iff this process is a sweep trial (reference analog:
    tune.is_session_enabled, reference tune.py:14-22)."""
    return _ctx is not None


def get_trial_id() -> str:
    assert _ctx is not None, "no trial session in this process"
    return _ctx.trial_id


def get_trial_dir() -> str:
    """Per-trial storage dir (the reference analog of
    ``tune.checkpoint_dir(step)``, reference tune.py:128-142 — but
    checkpoints are written in place by the trial, never shipped through
    the queue; SURVEY §2.4 scaling hazard, consciously fixed)."""
    assert _ctx is not None, "no trial session in this process"
    return _ctx.trial_dir


def report(metrics: Optional[Dict[str, Any]] = None,
           checkpoint: Optional[str] = None, **kw: Any) -> None:
    """``tune.report`` analog, usable directly inside a trainable."""
    assert _ctx is not None, "report() outside a trial session"
    merged = dict(metrics or {})
    merged.update(kw)
    _ctx.report(merged, checkpoint=checkpoint)


def get_trial_hosts() -> list:
    """Cluster hosts borrowed by this trial (``sweep.run(hosts=...)``),
    empty when the trial runs on the driver machine. The trial driver
    itself runs on the first; a nested ``fit_distributed(hosts=
    get_trial_hosts(), transport=...)`` spans all of them."""
    import os

    raw = os.environ.get("RLT_TRIAL_HOSTS", "")
    return [h for h in raw.split(",") if h]


def get_checkpoint() -> Optional[str]:
    """Checkpoint path to resume this trial from, or None on a fresh start.

    Set by the sweep runner when re-running an interrupted/errored trial
    (extends the reference's checkpoint registration, tune.py:128-142, with
    the restore direction Ray Tune gained later). Trainables opt in::

        def trainable(config):
            trainer.fit(module, data, ckpt_path=sweep.get_checkpoint())

    Works in the trial process (session-bound) and in nested SPMD workers
    (via the trial environment).
    """
    import os

    if _ctx is not None:
        return _ctx.last_checkpoint
    return os.environ.get("RLT_TRIAL_RESUME") or None
