"""The sweep runner: S concurrent trials, each a driver of its own workers.

Rebuild of the reference's signature three-level topology (SURVEY §3.3):
Tune driver -> trial actors -> training-worker actors, where each trial
runs the ENTIRE distributed-fit stack inside itself (reference
examples/ray_ddp_example.py:101-113; tests/test_tune.py). Here:

  sweep driver (this module)
    -> trial processes       (one runtime worker process per trial,
                              process-isolated like a Ray trial actor)
      -> training workers    (the trial calls Trainer.fit directly, or
                              fit_distributed to launch its own SPMD
                              worker group — the nested case)

Differences by design:
  * resource accounting is integral-slice (resources.py), not the
    reference's extra_cpu oversubscription trick (SURVEY §7.4 #4);
  * the report channel is duplex — the scheduler's verdict returns on the
    same socket and a stopped trial unwinds cooperatively via
    TrialStopped (schedulers.py), instead of Tune killing the actor;
  * checkpoints never transit the channel — trials write them in place
    and report paths (SURVEY §2.4 scaling hazard, consciously fixed).
"""
from __future__ import annotations

import os
import secrets
import threading
import traceback
from collections import deque
from multiprocessing.connection import Listener
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_lightning_tpu.analysis.lockwatch import san_lock
from ray_lightning_tpu.runtime.group import WorkerGroup, WorkerError
from ray_lightning_tpu.sweep import session as trial_session
from ray_lightning_tpu.sweep.analysis import ExperimentAnalysis, Trial
from ray_lightning_tpu.sweep.resources import ResourcePool, TpuResources
from ray_lightning_tpu.sweep.schedulers import (
    CONTINUE,
    FIFOScheduler,
    TrialScheduler,
)
from ray_lightning_tpu.sweep.space import expand
from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)


class SweepError(RuntimeError):
    pass


class _HostPool:
    """Free-list of cluster hosts for trial placement (the reference let
    Ray's scheduler put trial actors on any node; here placement is
    explicit: each process-executor trial borrows `resources.hosts` hosts
    for its lifetime and returns them)."""

    def __init__(self, hosts):
        self._free = list(hosts)
        self._lock = san_lock("sweep.tuner.hosts")

    def try_acquire(self, n: int):
        with self._lock:
            if len(self._free) < n:
                return None
            taken, self._free = self._free[:n], self._free[n:]
            return taken

    def release(self, hosts) -> None:
        with self._lock:
            self._free.extend(hosts)


def _probe_device_count(executor: str) -> int:
    """Default chip-pool size.

    With process-isolated trials the DRIVER must not initialize the
    accelerator backend (on TPU, libtpu is exclusively held by whichever
    process touches it first — the driver grabbing it would starve every
    trial's workers), so the topology is probed in a throwaway subprocess.
    Inline trials run in this process and will initialize jax anyway.
    """
    if executor == "inline":
        import jax

        return len(jax.devices())
    import subprocess
    import sys

    try:
        out = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=120,
        )
        return int(out.stdout.strip().splitlines()[-1])
    except Exception:  # noqa: BLE001 — fall back to a safe minimum
        log.warning("device-count probe failed; defaulting the pool to 1 "
                    "chip — pass total_chips explicitly")
        return 1


def _trial_main(trainable, config, trial_id, trial_dir, address, authkey_hex,
                resume_from=None):
    """Body of one trial — runs inside the trial's own worker process
    (the analog of the reference's trial-actor trainable,
    reference examples/ray_ddp_example.py:61-76)."""
    # The process env is the platform contract (the SPMD path asserts it
    # in _spmd_main; trials must too): site hooks that register a custom
    # jax backend can config.update jax_platforms at interpreter start,
    # OVERRIDING the JAX_PLATFORMS this trial was launched with — a
    # CPU-pinned trial would then silently initialize (and run on!) the
    # site's accelerator backend. Re-assert before any jax touch; if a
    # backend is somehow already live, leave it (update would raise).
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        try:
            import jax

            jax.config.update("jax_platforms", platforms)
        except Exception:  # noqa: BLE001 — initialized backends win
            pass
    ctx = trial_session.RemoteTrialContext(
        trial_id, trial_dir, address, bytes.fromhex(authkey_hex),
        last_checkpoint=resume_from,
    )
    trial_session.init_trial_session(ctx)
    # Nested SPMD workers launched by this trial inherit the trial identity
    # through the environment (sweep/callbacks.py resolves trial_dir from it
    # when the trial session object itself isn't bound in the worker).
    os.environ["RLT_TRIAL_ID"] = trial_id
    os.environ["RLT_TRIAL_DIR"] = trial_dir
    if resume_from:
        os.environ["RLT_TRIAL_RESUME"] = resume_from
    try:
        result = trainable(config)
        return (Trial.DONE, result)
    except trial_session.TrialStopped:
        return (Trial.STOPPED, None)
    finally:
        ctx.close()
        trial_session.reset_trial_session()


class _ReportServer:
    """Driver-side end of the duplex report channel: accepts one socket
    per trial, answers every report with the scheduler's verdict."""

    def __init__(self, handle_report: Callable[[str, Dict, Optional[str]], str],
                 bind_all: bool = False):
        self._handle = handle_report
        self._authkey = secrets.token_bytes(32)
        # Remote trials must reach the channel: bind the cluster-facing
        # interface and advertise its address (cf. WorkerGroup.start —
        # binding the SPECIFIC interface, not 0.0.0.0, keeps the
        # authenticated-but-cleartext pickle channel off networks no
        # trial dials in on; trusted-network assumption documented in
        # runtime/transport.py SECURITY note).
        from ray_lightning_tpu.runtime.group import routable_ip

        self._advertise = routable_ip() if bind_all else "127.0.0.1"
        if bind_all and self._advertise == "127.0.0.1":
            raise RuntimeError(
                "cannot determine a routable address for host-placed "
                "trials (no default route). Set RLT_NODE_IP to this "
                "machine's cluster-facing IP."
            )
        try:
            self._listener = Listener((self._advertise, 0),
                                      authkey=self._authkey)
        except OSError:
            # advertise may be a NAT/forwarded address that is valid to
            # dial but not a local interface (cf. WorkerGroup.start's
            # identical fallback)
            log.warning(
                "report-channel advertise address %s is not a local "
                "interface; binding 0.0.0.0 (ensure the network path to "
                "trials is trusted)", self._advertise,
            )
            self._listener = Listener(("0.0.0.0", 0), authkey=self._authkey)
        self._closed = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()

    @property
    def address(self) -> tuple:
        return (self._advertise, self._listener.address[1])

    @property
    def authkey_hex(self) -> str:
        return self._authkey.hex()

    def _accept_loop(self) -> None:
        # Split accept from authentication: with the listener possibly on
        # 0.0.0.0 for host-placed trials, a peer that stalls or resets
        # mid-auth-challenge must neither wedge nor kill the acceptor —
        # later trials still need to hand-shake. The socket-level accept
        # (internal but stable: SocketListener.accept returns the raw
        # Connection, no challenge) only ever blocks waiting for NEW
        # connections; the blocking challenge runs on the per-connection
        # thread, so a hostile peer wedges only its own thread.
        import time as _time

        while not self._closed:
            try:
                conn = self._listener._listener.accept()
            except Exception:  # noqa: BLE001 — keep serving
                if self._closed:
                    return  # listener closed by close()
                log.warning("report server: accept failed\n%s",
                            traceback.format_exc(limit=2))
                # bound a persistent failure (e.g. EMFILE) to a warm
                # trickle instead of a hot busy-loop flooding the log
                _time.sleep(0.2)
                continue
            threading.Thread(
                target=self._auth_and_serve, args=(conn,), daemon=True
            ).start()

    def _auth_and_serve(self, conn) -> None:
        from multiprocessing.connection import (
            answer_challenge,
            deliver_challenge,
        )

        try:
            deliver_challenge(conn, self._authkey)
            answer_challenge(conn, self._authkey)
        except Exception:  # noqa: BLE001 — scanner / wrong key / reset
            log.warning("report server: rejected connection\n%s",
                        traceback.format_exc(limit=2))
            try:
                conn.close()
            except OSError:
                pass
            return
        self._serve(conn)

    def _serve(self, conn) -> None:
        try:
            while True:
                msg = conn.recv()
                if msg[0] == "report":
                    _, trial_id, metrics, ckpt = msg
                    # A handler error must still produce a reply — the trial
                    # is blocked on recv() and would hang forever otherwise.
                    try:
                        verdict = self._handle(trial_id, metrics, ckpt)
                    except Exception:  # noqa: BLE001
                        log.error("report handler failed for %s:\n%s",
                                  trial_id, traceback.format_exc())
                        verdict = CONTINUE
                    conn.send(verdict)
                elif msg[0] in ("hello", "bye"):
                    if msg[0] == "bye":
                        return
                else:
                    log.warning("report server: unknown message %r", msg[0])
        except (EOFError, OSError):
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed = True
        self._listener.close()


class TrialRunner:
    def __init__(
        self,
        trainable: Callable[[Dict[str, Any]], Any],
        configs: List[Dict[str, Any]],
        *,
        metric: Optional[str],
        mode: str,
        scheduler: TrialScheduler,
        resources_per_trial: TpuResources,
        pool: ResourcePool,
        max_concurrent: Optional[int],
        storage_dir: str,
        executor: str,
        trial_timeout: Optional[float],
        env: Optional[Dict[str, str]],
        hosts: Optional[List[str]] = None,
        transport=None,
        retry_policy=None,
    ):
        self.trainable = trainable
        self.metric = metric
        self.mode = mode
        self.scheduler = scheduler
        self.resources = resources_per_trial
        self.pool = pool
        self.storage_dir = storage_dir
        self.executor = executor
        self.trial_timeout = trial_timeout
        self.env = env
        self.transport = transport
        #: trial-level retry (resilience/policy.py RetryPolicy): an
        #: infra-classified trial failure re-enqueues the trial, resuming
        #: from its last registered checkpoint, instead of burying a
        #: whole config under one flaky host
        self.retry_policy = retry_policy
        self.host_pool: Optional[_HostPool] = None
        if hosts:
            if transport is None or not transport.is_remote:
                # fail here, not inside the trial threads — a per-thread
                # ValueError would strand `running` and deadlock the sweep
                raise SweepError(
                    "hosts= requires a remote transport (e.g. SSHTransport)"
                )
            if resources_per_trial.hosts > len(hosts):
                raise SweepError(
                    f"one trial needs {resources_per_trial.hosts} hosts but "
                    f"only {len(hosts)} were given"
                )
            self.host_pool = _HostPool(hosts)
        cap = pool.max_concurrent(resources_per_trial)
        if cap < 1:
            raise SweepError(
                f"one trial needs {resources_per_trial.chips} chips but the "
                f"pool has {pool.total_chips}"
            )
        self.max_concurrent = min(max_concurrent or cap, cap)
        self._lock = san_lock("sweep.tuner.runner")
        self._cond = threading.Condition(self._lock)
        self.trials: List[Trial] = []
        for i, cfg in enumerate(configs):
            tid = f"trial_{i:05d}"
            tdir = os.path.join(storage_dir, tid)
            os.makedirs(tdir, exist_ok=True)
            trial = Trial(tid, cfg, tdir, resources_per_trial)
            # Resume: a rerun over an existing storage_dir restores each
            # trial's recorded progress; interrupted/errored trials restart
            # from their last registered checkpoint (extends reference
            # tune.py:128-142 with the restore direction).
            self._load_trial_state(trial)
            self.trials.append(trial)
        self._by_id = {t.trial_id: t for t in self.trials}

    # --------------------------------------------------------- persistence
    def _state_path(self, trial: Trial) -> str:
        return os.path.join(trial.trial_dir, "trial_state.json")

    def _snapshot_trial_state(self, trial: Trial) -> Tuple[str, Dict]:
        """Copy the mutable trial record (cheap, in-memory) — safe to
        call under self._lock; the file write happens outside it."""
        import json

        state = {
            "status": trial.status,
            "history": list(trial.history),
            "checkpoints": list(trial.checkpoints),
            "error": trial.error,
        }
        try:
            json.dumps(trial.result)
            state["result"] = trial.result
        except (TypeError, ValueError):
            pass  # non-JSON trainable return: status/history still persist
        return self._state_path(trial), state

    def _write_trial_state(self, trial_id: str, path: str,
                           state: Dict) -> None:
        import json

        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(state, f)
            os.replace(tmp, path)
        except (OSError, TypeError, ValueError) as exc:
            log.warning("could not persist %s state: %s", trial_id, exc)

    def _save_trial_state(self, trial: Trial) -> None:
        """Durable per-trial record (atomic rename) so a later sweep.run
        over the same storage_dir can skip DONE trials and resume the rest.
        Never call this holding self._lock — snapshot under the lock and
        write outside (threadcheck RLT705: every report thread and the
        scheduler loop contend on that lock; disk latency must not
        serialize them)."""
        path, state = self._snapshot_trial_state(trial)
        self._write_trial_state(trial.trial_id, path, state)

    def _load_trial_state(self, trial: Trial) -> None:
        import json

        path = self._state_path(trial)
        if not os.path.exists(path):
            return
        try:
            with open(path) as f:
                state = json.load(f)
        except (OSError, ValueError) as exc:
            log.warning("ignoring unreadable %s state: %s",
                        trial.trial_id, exc)
            return
        trial.history = list(state.get("history", []))
        if trial.history:
            trial.last_result = trial.history[-1]
        trial.checkpoints = list(state.get("checkpoints", []))
        trial.result = state.get("result")
        status = state.get("status")
        if status in (Trial.DONE, Trial.STOPPED):
            # terminal: DONE finished; STOPPED was the scheduler's
            # deliberate early-kill — resurrecting it would let an
            # intentionally-culled config back into the race
            trial.status = status
        # Anything else (error / a stale "running" from a crashed driver)
        # stays PENDING and will be re-scheduled, resuming from
        # trial.last_checkpoint if one was registered.

    # ------------------------------------------------------------- reports
    def _handle_report(self, trial_id: str, metrics: Dict[str, Any],
                       checkpoint: Optional[str]) -> str:
        with self._lock:
            trial = self._by_id.get(trial_id)
            if trial is None:
                log.warning("report from unknown trial %s", trial_id)
                return CONTINUE
            iteration = trial.iterations + 1
            record = dict(metrics)
            # Ray Tune parity: every report carries training_iteration
            # (asserted by the reference's tests, test_tune.py:44-45).
            record.setdefault("training_iteration", iteration)
            trial.history.append(record)
            trial.last_result = record
            if checkpoint:
                trial.checkpoints.append(checkpoint)
            key = self.scheduler.metric or self.metric
            value = record.get(key) if key else None
            try:
                value = float(value) if value is not None else None
            except (TypeError, ValueError):
                value = None  # non-numeric metric: scheduler sees no signal
            verdict = self.scheduler.on_result(trial_id, iteration, value)
            if verdict != CONTINUE:
                log.info("scheduler stopping %s at iteration %d", trial_id,
                         iteration)
            path, state = self._snapshot_trial_state(trial)
        # The state file write runs OUTSIDE self._lock: every report
        # thread and the scheduler loop contend on it, and a slow disk
        # must not serialize trial scheduling (RLT705 regression,
        # pinned by test_concurrency_lint.py).
        self._write_trial_state(trial_id, path, state)
        return verdict

    # --------------------------------------------------------------- retry
    def _retry_delay(self, trial: "Trial",
                     exc: BaseException) -> Optional[float]:
        """Backoff delay when this failure should be retried, else None.
        Reuses the resilience failure taxonomy: FATAL (a deterministic
        user exception) is never retried — replaying a bug N times would
        just burn the budget a flaky host needs."""
        if self.retry_policy is None:
            return None
        from ray_lightning_tpu.resilience.policy import classify_failure

        fc = classify_failure(exc)
        if not fc.restartable or trial.restarts >= self.retry_policy.max_restarts:
            return None
        trial.restarts += 1
        delay = self.retry_policy.next_delay(trial.restarts)
        log.warning(
            "trial %s: retry %d/%d in %.1fs after [%s/%s] %s "
            "(resuming from %s)", trial.trial_id, trial.restarts,
            self.retry_policy.max_restarts, delay, fc.kind, fc.cause,
            fc.detail, trial.last_checkpoint or "scratch")
        return delay

    # -------------------------------------------------------------- inline
    def _run_inline(self) -> None:
        for trial in self.trials:
            if trial.status in (Trial.DONE, Trial.STOPPED):
                log.info("skipping %s: already %s", trial.trial_id,
                         trial.status)
                self.scheduler.on_trial_complete(trial.trial_id)
                continue
            self._run_inline_trial(trial)
            self.scheduler.on_trial_complete(trial.trial_id)
            self._save_trial_state(trial)

    def _run_inline_trial(self, trial: "Trial") -> None:
        import time as _time

        while True:
            trial.status = Trial.RUNNING
            # rebuilt per attempt: a retry must resume from the LAST
            # registered checkpoint, not the one the first attempt saw
            ctx = trial_session.LocalTrialContext(
                trial.trial_id, trial.trial_dir, self._handle_report,
                last_checkpoint=trial.last_checkpoint,
            )
            trial_session.init_trial_session(ctx)
            saved_env = {k: os.environ.get(k)
                         for k in ("RLT_TRIAL_ID", "RLT_TRIAL_DIR",
                                   "RLT_TRIAL_RESUME")}
            os.environ["RLT_TRIAL_ID"] = trial.trial_id
            os.environ["RLT_TRIAL_DIR"] = trial.trial_dir
            if trial.last_checkpoint:
                os.environ["RLT_TRIAL_RESUME"] = trial.last_checkpoint
            retry_in: Optional[float] = None
            try:
                trial.result = self.trainable(trial.config)
                trial.status = Trial.DONE
            except trial_session.TrialStopped:
                trial.status = Trial.STOPPED
            except BaseException as exc:  # noqa: BLE001 — recorded per trial
                retry_in = self._retry_delay(trial, exc)
                if retry_in is None:
                    trial.status = Trial.ERROR
                    trial.error = traceback.format_exc()
                    log.error("trial %s failed: %s", trial.trial_id, exc)
            finally:
                trial_session.reset_trial_session()
                for k, v in saved_env.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
            if retry_in is None:
                return
            _time.sleep(retry_in)

    # ------------------------------------------------------------- process
    def _run_process(self) -> None:
        server = _ReportServer(
            self._handle_report,
            # only trials actually placed off-machine need a routable
            # report channel; otherwise stay on loopback
            bind_all=self.host_pool is not None,
        )
        terminal = (Trial.DONE, Trial.STOPPED)
        for t in self.trials:
            if t.status in terminal:
                log.info("skipping %s: already %s", t.trial_id, t.status)
                self.scheduler.on_trial_complete(t.trial_id)
        pending = deque(t for t in self.trials if t.status not in terminal)
        running: set = set()
        try:
            with self._cond:
                while pending or running:
                    while pending and len(running) < self.max_concurrent:
                        if not self.pool.try_acquire(self.resources):
                            break
                        trial_hosts = None
                        if self.host_pool is not None:
                            trial_hosts = self.host_pool.try_acquire(
                                self.resources.hosts
                            )
                            if trial_hosts is None:
                                self.pool.release(self.resources)
                                break
                        trial = pending.popleft()
                        running.add(trial.trial_id)
                        trial.status = Trial.RUNNING
                        threading.Thread(
                            target=self._trial_thread,
                            args=(trial, server, running, trial_hosts,
                                  pending),
                            daemon=True,
                        ).start()
                    self._cond.wait(timeout=1.0)
        finally:
            server.close()

    def _trial_thread(self, trial: Trial, server: _ReportServer,
                      running: set, trial_hosts=None,
                      pending: Optional[deque] = None) -> None:
        group = None
        retry_in: Optional[float] = None
        try:
            env = {**(self.env or {}),
                   "RLT_TRIAL_ID": trial.trial_id,
                   "RLT_TRIAL_DIR": trial.trial_dir}
            if trial_hosts:
                # the FULL borrowed host set rides the env so the trial's
                # nested fit_distributed can span all of them
                # (sweep.get_trial_hosts())
                env["RLT_TRIAL_HOSTS"] = ",".join(trial_hosts)
            # cross-host trial placement: the trial-driver process runs on
            # its first borrowed host (reference: Ray scheduled trial
            # actors on any node); nested SPMD workers launch from there
            group = WorkerGroup(
                num_workers=1,
                env=env,
                log_dir=os.path.join(trial.trial_dir, "logs"),
                hosts=trial_hosts[:1] if trial_hosts else None,
                transport=self.transport if trial_hosts else None,
            )
            group.start()
            [out] = group.run(
                _trial_main,
                per_rank_args=[(self.trainable, trial.config, trial.trial_id,
                                trial.trial_dir, server.address,
                                server.authkey_hex, trial.last_checkpoint)],
                timeout=self.trial_timeout,
            )
            trial.status, trial.result = out
        except WorkerError as exc:
            retry_in = self._retry_delay(trial, exc)
            if retry_in is None:
                trial.status = Trial.ERROR
                trial.error = exc.traceback_str
                log.error("trial %s failed:\n%s", trial.trial_id,
                          exc.traceback_str)
        except BaseException as exc:  # noqa: BLE001 — recorded per trial
            retry_in = self._retry_delay(trial, exc)
            if retry_in is None:
                trial.status = Trial.ERROR
                trial.error = traceback.format_exc()
                log.error("trial %s infra failure:\n%s", trial.trial_id,
                          trial.error)
        finally:
            if group is not None:
                group.shutdown()
            self.pool.release(self.resources)
            if trial_hosts and self.host_pool is not None:
                self.host_pool.release(trial_hosts)
            if retry_in is None:
                # terminal outcome only — a retried trial is not complete
                self.scheduler.on_trial_complete(trial.trial_id)
            self._save_trial_state(trial)
            if retry_in is not None:
                # resources are released; the backoff costs only this
                # daemon thread and one concurrency slot
                import time as _time

                _time.sleep(retry_in)
            with self._cond:
                if retry_in is not None and pending is not None:
                    trial.status = Trial.PENDING
                    pending.append(trial)
                running.discard(trial.trial_id)
                self._cond.notify_all()

    # ----------------------------------------------------------------- run
    def run(self) -> List[Trial]:
        if self.executor == "inline":
            self._run_inline()
        elif self.executor == "process":
            self._run_process()
        else:
            raise ValueError(f"unknown executor {self.executor!r}")
        return self.trials


def run(
    trainable: Callable[[Dict[str, Any]], Any],
    config: Optional[Dict[str, Any]] = None,
    *,
    num_samples: int = 1,
    metric: Optional[str] = None,
    mode: str = "min",
    scheduler: Optional[TrialScheduler] = None,
    resources_per_trial: Optional[TpuResources] = None,
    total_chips: Optional[int] = None,
    total_cpus: Optional[int] = None,
    max_concurrent: Optional[int] = None,
    storage_dir: Optional[str] = None,
    name: str = "sweep",
    executor: str = "process",
    trial_timeout: Optional[float] = None,
    env: Optional[Dict[str, str]] = None,
    hosts: Optional[List[str]] = None,
    transport=None,
    seed: int = 0,
    raise_on_failed_trial: bool = True,
    retry_policy=None,
) -> ExperimentAnalysis:
    """``tune.run`` analog (reference examples/ray_ddp_example.py:101-113).

    ``trainable(config)`` runs once per trial; inside it, ``sweep.report``
    (directly or via the TuneReportCallback family) streams metrics back.
    ``executor="process"`` gives Ray-Tune-style per-trial process isolation
    (each trial may itself launch an SPMD worker group); ``"inline"`` runs
    trials sequentially in this process (debug / single-host).

    ``total_chips`` is the pool the reserve-don't-occupy accounting carves
    integral per-trial blocks out of; it defaults to the number of visible
    devices (one v5p slice on a pod, the virtual CPU mesh in tests).

    ``hosts`` + a remote ``transport`` (runtime/transport.py) place each
    process-executor trial on a borrowed cluster host for its lifetime —
    the reference's "Tune schedules trial actors anywhere" capability;
    concurrency is additionally bounded by ``len(hosts) //
    resources_per_trial.hosts``. Ignored by the inline executor.

    ``retry_policy`` (resilience.RetryPolicy) retries trials whose
    failure classifies as infrastructure (a killed worker process, a
    timeout, a backend loss) up to ``max_restarts`` times with capped
    exponential backoff, resuming from the trial's last registered
    checkpoint; FATAL user exceptions still fail the trial immediately.
    """
    if mode not in ("min", "max"):
        raise ValueError("mode must be 'min' or 'max'")
    configs = expand(config or {}, num_samples=num_samples, seed=seed)
    if not configs:
        raise ValueError("empty search space")
    scheduler = scheduler or FIFOScheduler()
    if scheduler.metric is None:
        scheduler.metric = metric
        scheduler.mode = mode
    resources_per_trial = resources_per_trial or TpuResources()
    if total_chips is None:
        total_chips = max(_probe_device_count(executor),
                          resources_per_trial.chips)
    if total_cpus is None and resources_per_trial.cpus > 0:
        # trials reserve CPUs -> account against this machine's cores
        # (reference analog: Tune's cluster CPU pool)
        total_cpus = max(os.cpu_count() or 1, resources_per_trial.cpus)
    pool = ResourcePool(total_chips, total_cpus)
    storage_dir = storage_dir or os.path.join(os.getcwd(), "rlt_sweeps", name)
    os.makedirs(storage_dir, exist_ok=True)

    runner = TrialRunner(
        trainable, configs,
        metric=metric, mode=mode, scheduler=scheduler,
        resources_per_trial=resources_per_trial, pool=pool,
        max_concurrent=max_concurrent, storage_dir=storage_dir,
        executor=executor, trial_timeout=trial_timeout, env=env,
        hosts=hosts, transport=transport, retry_policy=retry_policy,
    )
    log.info("sweep %s: %d trials, <=%d concurrent, %d chips/trial of %d",
             name, len(runner.trials), runner.max_concurrent,
             resources_per_trial.chips, total_chips)
    trials = runner.run()
    analysis = ExperimentAnalysis(trials, metric, mode)
    failed = analysis.errors()
    if failed and raise_on_failed_trial:
        detail = "\n".join(f"--- {k} ---\n{v}" for k, v in failed.items())
        raise SweepError(f"{len(failed)} trial(s) failed:\n{detail}")
    return analysis
