"""Trial schedulers: decide continue/stop on every report.

The reference delegated scheduling to Ray Tune (``ASHAScheduler`` in its
examples, reference examples/ray_ddp_example.py:101-106 passes
``num_samples``/scheduler through ``tune.run``). The rebuild owns the
decision point: every ``report()`` from a trial is routed through the
scheduler, whose verdict rides back on the same duplex channel — so a
stopped trial unwinds immediately (raising ``TrialStopped`` inside the
trial process), which on TPU also tears down the trial's whole device
group rather than wasting slice-hours.
"""
from __future__ import annotations

import math
from collections import defaultdict
from typing import Dict, List, Optional

CONTINUE = "continue"
STOP = "stop"


class TrialScheduler:
    """Base: sees (trial_id, iteration, metric value), returns a verdict."""

    #: sweep-level metric/mode are injected by the runner if the scheduler
    #: was constructed without them.
    metric: Optional[str] = None
    mode: str = "min"

    def on_result(self, trial_id: str, iteration: int,
                  value: Optional[float]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str) -> None:  # noqa: B027
        pass

    def _sign(self) -> float:
        # normalize so that LOWER is always better internally
        return 1.0 if self.mode == "min" else -1.0


class FIFOScheduler(TrialScheduler):
    """No early stopping: every trial runs to its own completion."""


class ASHAScheduler(TrialScheduler):
    """Asynchronous Successive Halving (stopping variant).

    Rungs at ``grace_period * reduction_factor**k`` up to ``max_t``. When a
    trial reaches a rung it records its metric there; it continues only if
    it is in the top ``1/reduction_factor`` of everything recorded at that
    rung so far. Asynchronous: decisions never wait for stragglers, so TPU
    slices freed by a stopped trial go straight back into the pool.
    """

    def __init__(self, metric: Optional[str] = None, mode: str = "min",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 3):
        if reduction_factor < 2:
            raise ValueError("reduction_factor must be >= 2")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = max(1, grace_period)
        self.rf = reduction_factor
        self.milestones: List[int] = []
        t = self.grace_period
        while t < max_t:
            self.milestones.append(t)
            t *= self.rf
        # rung milestone -> recorded (sign*value) list
        self._rungs: Dict[int, List[float]] = defaultdict(list)
        self._recorded: Dict[str, set] = defaultdict(set)

    def on_result(self, trial_id: str, iteration: int,
                  value: Optional[float]) -> str:
        if value is None or math.isnan(value):
            return CONTINUE
        s = self._sign() * float(value)
        for m in self.milestones:
            if iteration >= m and m not in self._recorded[trial_id]:
                self._recorded[trial_id].add(m)
                rung = self._rungs[m]
                rung.append(s)
                if len(rung) < self.rf:
                    continue  # not enough evidence at this rung yet
                k = max(1, len(rung) // self.rf)
                cutoff = sorted(rung)[k - 1]
                if s > cutoff:
                    return STOP
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running average is worse than the median of the
    other trials' running averages (after ``grace_period`` iterations and
    ``min_samples`` peer trials)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "min",
                 grace_period: int = 1, min_samples: int = 3):
        self.metric = metric
        self.mode = mode
        self.grace_period = max(1, grace_period)
        self.min_samples = min_samples
        self._sums: Dict[str, float] = defaultdict(float)
        self._counts: Dict[str, int] = defaultdict(int)

    def _running_avg(self, trial_id: str) -> float:
        return self._sums[trial_id] / max(1, self._counts[trial_id])

    def on_result(self, trial_id: str, iteration: int,
                  value: Optional[float]) -> str:
        if value is None or math.isnan(value):
            return CONTINUE
        self._sums[trial_id] += self._sign() * float(value)
        self._counts[trial_id] += 1
        if iteration < self.grace_period:
            return CONTINUE
        peers = [self._running_avg(t) for t in self._counts if t != trial_id]
        if len(peers) < self.min_samples:
            return CONTINUE
        median = sorted(peers)[len(peers) // 2]
        if self._running_avg(trial_id) > median:
            return STOP
        return CONTINUE
