"""Sweep-aware trainer callbacks: report metrics / register checkpoints.

Rebuild of the reference's Tune callbacks (reference tune.py:26-199):

  * TuneReportCallback — maps ``trainer.callback_metrics`` to report names
    (str / list / dict forms, reference tune.py:68-95) and ships them to
    the sweep scheduler from worker rank 0.
  * TuneReportCheckpointCallback — checkpoint-then-report, so the sweep
    registers the checkpoint with the metrics (reference tune.py:144-199).

Transport differences, by design:
  * the reference enqueued ``lambda: tune.report(...)`` for the trial
    driver to execute (reference tune.py:97-101, util.py:88-93). Here the
    same trampoline exists for the NESTED case (trainer running inside an
    SPMD worker group launched by the trial: rank 0 enqueues the report
    closure, the trial process executes it and blocks on the scheduler's
    verdict) — but when the trainer runs directly in the trial process the
    report is a direct duplex call, no queue hop.
  * checkpoints are written in place by the trial and only their PATH is
    reported — never the state dict through the channel (the reference
    shipped full checkpoint dicts through the queue actor per epoch,
    tune.py:128-142; SURVEY §2.4 flags that as a scaling hazard for
    8B-param models, consciously fixed here).
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Union

from ray_lightning_tpu.core.callbacks import Callback
from ray_lightning_tpu.runtime import session as runtime_session
from ray_lightning_tpu.sweep import session as trial_session
from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)


def _dispatch_report(report: Dict[str, Any],
                     checkpoint: Optional[str] = None) -> None:
    """Route a report to the sweep driver from wherever we are running.

    trial process  -> direct duplex report (blocks for the verdict);
    SPMD worker    -> rank 0 enqueues a report closure; the trial-side
                      pump executes it (the reference's trampoline,
                      util.py:88-93) and the verdict unwinds the pump;
    no sweep       -> no-op (trainer usable unchanged outside sweeps,
                      like the reference's is_session_enabled() fallback,
                      reference tune.py:14-22).
    """
    if trial_session.is_trial_session_enabled():
        trial_session.report(report, checkpoint=checkpoint)
    elif runtime_session.is_session_enabled():
        if runtime_session.get_actor_rank() == 0:
            runtime_session.put_queue(
                lambda: trial_session.report(report, checkpoint=checkpoint)
            )
    else:
        log.debug("report outside any sweep session: %s", report)


class TuneReportCallback(Callback):
    """Report trainer metrics to the sweep on a cadence.

    ``metrics`` forms (reference tune.py:41-66):
      None         — report all of trainer.callback_metrics;
      "loss"       — report that one, under its own name;
      ["a", "b"]   — report those;
      {"out": "in"}— report trainer metric "in" under name "out".
    ``on`` — "validation_end" (default) or "train_epoch_end".
    """

    def __init__(
        self,
        metrics: Union[None, str, List[str], Dict[str, str]] = None,
        on: str = "validation_end",
    ):
        if on not in ("validation_end", "train_epoch_end"):
            raise ValueError(f"unsupported report point {on!r}")
        self.metrics = metrics
        self.on = on

    def _collect(self, trainer,
                 extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        source = dict(trainer.callback_metrics)
        source.update(extra or {})
        if self.metrics is None:
            items = {k: v for k, v in source.items()}
        elif isinstance(self.metrics, str):
            items = {self.metrics: source.get(self.metrics)}
        elif isinstance(self.metrics, dict):
            items = {out: source.get(src) for out, src in self.metrics.items()}
        else:
            items = {m: source.get(m) for m in self.metrics}
        report = {}
        for k, v in items.items():
            if v is None:
                continue
            try:
                report[k] = float(v)
            except (TypeError, ValueError):
                pass  # non-scalar metrics don't cross the channel
        return report

    def _fire(self, trainer, extra=None) -> None:
        report = self._collect(trainer, extra)
        if report:
            _dispatch_report(report, checkpoint=self._checkpoint(trainer))

    def _checkpoint(self, trainer) -> Optional[str]:
        return None  # overridden by the checkpointing variant

    def on_validation_epoch_end(self, trainer, module, metrics) -> None:
        if self.on == "validation_end":
            self._fire(trainer, extra=metrics)

    def on_train_epoch_end(self, trainer, module) -> None:
        if self.on == "train_epoch_end" or (
            self.on == "validation_end" and not trainer.has_validation
        ):
            self._fire(trainer)


class TuneReportCheckpointCallback(TuneReportCallback):
    """Checkpoint-then-report (reference tune.py:144-199 ordering, so the
    sweep registers the checkpoint alongside the metrics).

    The checkpoint lands under the trial dir, resolved in priority order:
    explicit ``dirpath`` > the trial session (trainer running in the trial
    process) > the ``RLT_TRIAL_DIR`` environment the trial runner exports
    (trainer running in nested SPMD workers, which inherit the trial's
    env) > the trainer's root dir. Written as a sharded orbax checkpoint —
    every worker writes its addressable shards.
    """

    def __init__(
        self,
        metrics: Union[None, str, List[str], Dict[str, str]] = None,
        filename: str = "checkpoint",
        on: str = "validation_end",
        dirpath: Optional[str] = None,
        keep_last_n: Optional[int] = None,
    ):
        super().__init__(metrics=metrics, on=on)
        self.filename = filename
        self.dirpath = dirpath
        #: retention: keep only the newest N checkpoints this callback
        #: wrote (None = keep all). A per-epoch cadence over a long sweep
        #: otherwise fills the disk with full model+optimizer states.
        if keep_last_n is not None and keep_last_n < 1:
            raise ValueError(f"keep_last_n must be >= 1, got {keep_last_n}")
        self.keep_last_n = keep_last_n
        self._written: List[str] = []

    def _resolve_dir(self, trainer) -> str:
        if self.dirpath:
            return self.dirpath
        if trial_session.is_trial_session_enabled():
            return trial_session.get_trial_dir()
        env_dir = os.environ.get("RLT_TRIAL_DIR")
        if env_dir:
            return env_dir
        return os.path.join(trainer.default_root_dir, "sweep_checkpoints")

    def _checkpoint(self, trainer) -> Optional[str]:
        base = self._resolve_dir(trainer)
        path = os.path.join(
            base, f"{self.filename}_{trainer.global_step:08d}"
        )
        out = trainer.save_checkpoint(path)
        if self.keep_last_n is not None:
            # re-saving an existing path (e.g. a zero-step epoch writing
            # the same global_step) must replace, not duplicate, its
            # entry — a duplicate would let prune delete the live newest
            self._written = [p for p in self._written if p != out]
            self._written.append(out)
            self._prune()
        return out

    def _prune(self) -> None:
        """Delete this callback's oldest checkpoints beyond keep_last_n.
        Only rank 0 removes files (a sharded write is collective, but the
        dirs live on a shared filesystem); only paths THIS callback wrote
        are ever touched. _written mutates identically on every rank so
        the bookkeeping stays in step.

        Retention floor (trainguard): when every checkpoint inside the
        keep window is explicitly UNblessed (written during an anomaly
        streak), the newest blessed one outside it is exempted — the
        trial's rollback restore point must survive the window sliding
        past it."""
        import jax

        from ray_lightning_tpu.core.callbacks import (
            _ckpt_blessed,
            _remove_checkpoint,
        )

        excess = len(self._written) - self.keep_last_n
        if excess <= 0:
            return
        victims, kept = self._written[:excess], self._written[excess:]
        protected = None
        if not any(_ckpt_blessed(p) is True for p in kept):
            for p in reversed(victims):  # newest blessed victim
                if _ckpt_blessed(p) is True:
                    protected = p
                    break
        for victim in victims:
            if victim == protected:
                continue
            if jax.process_index() == 0:
                _remove_checkpoint(victim)
                log.info("pruned sweep checkpoint %s", victim)
        self._written = ([protected] if protected else []) + kept
