"""Search-space primitives and config sampling for HPO sweeps.

The reference delegated search spaces to Ray Tune (``tune.choice`` /
``tune.loguniform`` / ``tune.grid_search`` used in its examples,
reference examples/ray_ddp_example.py:95-99, ray_ddp_tune.py:90-94).
The rebuild owns them: a space is a plain dict whose leaves may be
samplers; ``expand()`` turns it into the concrete trial-config list —
grid entries cross-product, samplers draw per sample, deterministic
under a seed.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Any, Dict, List, Sequence

import numpy as np


class Sampler:
    """A randomly-drawn hyperparameter leaf."""

    def sample(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError


@dataclass(frozen=True)
class Choice(Sampler):
    values: tuple

    def sample(self, rng: np.random.Generator) -> Any:
        return self.values[int(rng.integers(0, len(self.values)))]


@dataclass(frozen=True)
class Uniform(Sampler):
    low: float
    high: float

    def sample(self, rng: np.random.Generator) -> float:
        return float(rng.uniform(self.low, self.high))


@dataclass(frozen=True)
class LogUniform(Sampler):
    low: float
    high: float

    def sample(self, rng: np.random.Generator) -> float:
        return float(np.exp(rng.uniform(np.log(self.low), np.log(self.high))))


@dataclass(frozen=True)
class RandInt(Sampler):
    low: int
    high: int  # exclusive, numpy convention

    def sample(self, rng: np.random.Generator) -> int:
        return int(rng.integers(self.low, self.high))


@dataclass(frozen=True)
class GridSearch:
    """Exhaustive axis: the config list is the cross-product of all grid
    axes, repeated ``num_samples`` times (Ray Tune semantics)."""

    values: tuple


def choice(values: Sequence[Any]) -> Choice:
    return Choice(tuple(values))


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def grid_search(values: Sequence[Any]) -> GridSearch:
    return GridSearch(tuple(values))


def expand(
    space: Dict[str, Any], num_samples: int = 1, seed: int = 0
) -> List[Dict[str, Any]]:
    """Materialize a space into concrete trial configs.

    Count = (product of grid axis lengths) x num_samples; sampler leaves
    are drawn independently per config; plain values pass through.
    """
    grid_keys = [k for k, v in space.items() if isinstance(v, GridSearch)]
    grid_axes = [space[k].values for k in grid_keys]
    rng = np.random.default_rng(seed)

    configs: List[Dict[str, Any]] = []
    for _ in range(num_samples):
        for combo in itertools.product(*grid_axes) if grid_keys else [()]:
            cfg: Dict[str, Any] = {}
            for k, v in space.items():
                if isinstance(v, GridSearch):
                    cfg[k] = combo[grid_keys.index(k)]
                elif isinstance(v, Sampler):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            configs.append(cfg)
    return configs
