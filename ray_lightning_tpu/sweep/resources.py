"""TPU resource accounting for concurrent trials.

SURVEY §7.4 hard part #4: the reference reserved training-worker capacity
with Ray Tune's ``extra_cpu``/``extra_gpu`` oversubscription trick
(reference examples/ray_ddp_example.py:107-112) — the trial actor occupies
1 CPU and *reserves* N more for the workers it will launch. That trick has
no TPU analog: a trial must own an **integral device group** (a slice /
host group) because ICI collectives span the whole group. So the sweep
layer does the accounting itself: a ``ResourcePool`` of total chips, each
trial acquiring an integral ``TpuResources`` block, concurrency =
floor(total / per-trial) — reserve-don't-occupy, enforced by the trial
runner rather than by a cluster scheduler.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional

from ray_lightning_tpu.analysis.lockwatch import san_lock


@dataclass(frozen=True)
class TpuResources:
    """What ONE trial reserves.

    chips  — devices the trial's mesh will span (its workers occupy them).
    hosts  — host processes the trial will launch (driver-side bookkeeping
             only; on CI these are subprocesses, on a pod they are per-host
             runtimes).
    cpus   — host CPUs the trial's workers consume (data pipeline /
             prefetch threads). The reference let trials reserve CPUs
             independently of accelerators (num_cpus_per_worker +
             extra_cpu, reference ray_ddp.py:89-111 and
             examples/ray_ddp_example.py:107-112); 0 = unaccounted.
    """

    chips: int = 1
    hosts: int = 1
    cpus: int = 0

    def __post_init__(self):
        if self.chips < 1 or self.hosts < 1:
            raise ValueError(f"resources must be >= 1, got {self}")
        if self.cpus < 0:
            raise ValueError(f"cpus must be >= 0, got {self}")


class ResourcePool:
    """Thread-safe integral-block allocator over fixed chip + CPU budgets.

    Chips are the primary (integral-slice) constraint; CPUs are the
    secondary one — trial packing is bounded by whichever runs out first
    (the reference's extra_cpu reserve-don't-occupy accounting,
    examples/ray_ddp_example.py:107-112, without the oversubscription
    trick)."""

    def __init__(self, total_chips: int, total_cpus: Optional[int] = None):
        if total_chips < 1:
            raise ValueError("total_chips must be >= 1")
        if total_cpus is not None and total_cpus < 1:
            raise ValueError("total_cpus must be >= 1 when given")
        self.total_chips = total_chips
        self.total_cpus = total_cpus
        self._in_use = 0
        self._cpus_in_use = 0
        self._lock = san_lock("sweep.resources.pool")

    def max_concurrent(self, per_trial: TpuResources) -> int:
        """floor(topology / per-trial shape) — SURVEY §7.4 #4 — jointly
        over every accounted dimension."""
        cap = self.total_chips // per_trial.chips
        if self.total_cpus is not None and per_trial.cpus > 0:
            cap = min(cap, self.total_cpus // per_trial.cpus)
        return cap

    def try_acquire(self, res: TpuResources) -> bool:
        with self._lock:
            if res.chips > self.total_chips:
                raise ValueError(
                    f"trial wants {res.chips} chips but the pool only has "
                    f"{self.total_chips} — an integral slice cannot be "
                    "oversubscribed"
                )
            if self.total_cpus is not None and res.cpus > self.total_cpus:
                raise ValueError(
                    f"trial wants {res.cpus} cpus but the pool only has "
                    f"{self.total_cpus}"
                )
            if self._in_use + res.chips > self.total_chips:
                return False
            if (self.total_cpus is not None
                    and self._cpus_in_use + res.cpus > self.total_cpus):
                return False
            self._in_use += res.chips
            self._cpus_in_use += res.cpus
            return True

    def release(self, res: TpuResources) -> None:
        with self._lock:
            self._in_use = max(0, self._in_use - res.chips)
            self._cpus_in_use = max(0, self._cpus_in_use - res.cpus)

    @property
    def in_use(self) -> int:
        with self._lock:
            return self._in_use

    @property
    def cpus_in_use(self) -> int:
        with self._lock:
            return self._cpus_in_use
