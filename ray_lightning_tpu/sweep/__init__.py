"""HPO sweep layer — the rebuild of the reference's Ray Tune integration
(reference tune.py + examples/ray_ddp_tune.py; SURVEY §3.3, §7.2 L5').

Surface:
    analysis = sweep.run(trainable, config={...}, num_samples=8,
                         metric="val_loss", mode="min",
                         scheduler=sweep.ASHAScheduler(),
                         resources_per_trial=sweep.TpuResources(chips=4))
    analysis.best_config / analysis.best_checkpoint

Inside a trainable: ``sweep.report(loss=...)`` directly, or attach
``TuneReportCallback`` / ``TuneReportCheckpointCallback`` to the Trainer.
"""
from ray_lightning_tpu.sweep.analysis import ExperimentAnalysis, Trial
from ray_lightning_tpu.sweep.callbacks import (
    TuneReportCallback,
    TuneReportCheckpointCallback,
)
from ray_lightning_tpu.sweep.resources import ResourcePool, TpuResources
from ray_lightning_tpu.sweep.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    TrialScheduler,
)
from ray_lightning_tpu.sweep.session import (
    TrialStopped,
    get_checkpoint,
    get_trial_dir,
    get_trial_hosts,
    get_trial_id,
    is_trial_session_enabled,
    report,
)
from ray_lightning_tpu.sweep.space import (
    choice,
    expand,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_lightning_tpu.sweep.tuner import SweepError, run

__all__ = [
    "run",
    "SweepError",
    "ExperimentAnalysis",
    "Trial",
    "TuneReportCallback",
    "TuneReportCheckpointCallback",
    "TpuResources",
    "ResourcePool",
    "TrialScheduler",
    "FIFOScheduler",
    "ASHAScheduler",
    "MedianStoppingRule",
    "report",
    "get_trial_id",
    "get_trial_dir",
    "get_checkpoint",
    "get_trial_hosts",
    "is_trial_session_enabled",
    "TrialStopped",
    "choice",
    "uniform",
    "loguniform",
    "randint",
    "grid_search",
    "expand",
]
