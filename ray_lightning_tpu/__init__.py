"""ray_lightning_tpu — a TPU-native training framework.

A ground-up rebuild of the capabilities of `ray_lightning`
(aced125/ray_lightning: Lightning-on-Ray distributed training plugins) as
an idiomatic JAX/XLA framework: Lightning-style Module/Trainer, sharding
strategies over a `jax.sharding.Mesh` (DP / FSDP / tensor / sequence
parallel), a multi-host runtime substrate, sharded checkpointing, and a
Tune-style HPO sweep layer — no torch, no NCCL, no Ray in the loop.
"""
from ray_lightning_tpu.core import (
    Callback,
    DataLoader,
    DataModule,
    EarlyStopping,
    ModelCheckpoint,
    ProgressLogger,
    MemoryMonitor,
    ThroughputMonitor,
    TpuModule,
    TrainState,
    Trainer,
)
from ray_lightning_tpu.parallel import (
    DataParallel,
    FSDP,
    MeshSpec,
    RayXlaPlugin,
    ShardedMesh,
    SingleDevice,
    Strategy,
    make_mesh,
)
from ray_lightning_tpu.runtime import (
    WorkerError,
    WorkerGroup,
    launch,
    launch_cpu_spmd,
)
from ray_lightning_tpu.utils import seed_everything, simulate_cpu_devices
from ray_lightning_tpu import pipeline, sweep
from ray_lightning_tpu.pipeline import DevicePrefetcher
from ray_lightning_tpu.resilience import (
    GuardCallback,
    GuardConfig,
    ResilienceConfig,
    RetryPolicy,
    SupervisedResult,
    fit_supervised,
    supervise,
)
from ray_lightning_tpu import telemetry
from ray_lightning_tpu.telemetry import ProfileConfig, TelemetryConfig
from ray_lightning_tpu import elastic
from ray_lightning_tpu.elastic import ElasticBudget, reshard_restore

__version__ = "0.1.0"

__all__ = [
    "TpuModule",
    "Trainer",
    "TrainState",
    "DataLoader",
    "DataModule",
    "Callback",
    "EarlyStopping",
    "ModelCheckpoint",
    "ProgressLogger",
    "MemoryMonitor",
    "ThroughputMonitor",
    "Strategy",
    "DataParallel",
    "FSDP",
    "ShardedMesh",
    "SingleDevice",
    "RayXlaPlugin",
    "MeshSpec",
    "make_mesh",
    "WorkerError",
    "WorkerGroup",
    "launch",
    "launch_cpu_spmd",
    "seed_everything",
    "simulate_cpu_devices",
    "sweep",
    "pipeline",
    "DevicePrefetcher",
    "GuardCallback",
    "GuardConfig",
    "ResilienceConfig",
    "RetryPolicy",
    "SupervisedResult",
    "fit_supervised",
    "supervise",
    "telemetry",
    "TelemetryConfig",
    "ProfileConfig",
    "elastic",
    "ElasticBudget",
    "reshard_restore",
    "__version__",
]
