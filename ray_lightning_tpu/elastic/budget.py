"""Elastic supervision budgets: which world sizes a job may legally
run at, and what each size does to the batch plan.

The resilience supervisor used to know exactly one world size: lose a
host past the retry budget and the run died with capacity idling. An
`ElasticBudget` gives it a ladder instead (docs/ELASTIC.md "elastic
supervision"): on a failure the supervisor may move DOWN the ladder
(reshard the latest valid checkpoint onto the largest legal survivor
mesh and resume smaller) and back UP when capacity returns — each rung
validated by the same divisibility machinery the pre-flight plan
checker uses (`MeshSpec.resolve` + `plan.dp_degree`), never by
guesswork.

Legality of a world size ``w``:

  * ``min_world <= w <= max_world`` (max defaults to the launch size);
  * ``w % divisible_by == 0``;
  * the job's mesh template resolves at ``w`` — ``spec_for(w)`` must
    not raise (default template: all-data, which any w satisfies; pass
    the job's real template, e.g. ``lambda w: MeshSpec(fsdp=w)`` or a
    fixed-tensor shape ``lambda w: MeshSpec(data=-1, tensor=4)``, to
    get real divisibility checking);
  * when ``global_batch`` is set, it must shard at ``w``:
    ``global_batch % dp_degree(spec) == 0``.

The batch story is reported HONESTLY (`batch_plan`): shrinking dp
shrinks the global batch unless gradient accumulation makes up the
difference — the plan names the accumulation factor that would preserve
it (`Trainer(accumulate_grad_batches=...)`) and whether it is whole;
the supervisor records the plan in its reshard ledger either way, so a
silently changed effective batch can never masquerade as a seamless
resume.

Capacity comes from the SHARED oracle (`autoscale/capacity.py`,
docs/AUTOSCALE.md) — the same truth the serving autoscaler's clamp
reads: `RLT_CAPACITY` env override, probe file, optional WorkerGroup
spawn probe, with the resolved-max fallback LABELED ``assumed`` so the
supervisor can record the honesty gap in the reshard ledger when a
grow is refused (the old silent assume-restored default is retired).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

from ray_lightning_tpu.parallel.mesh import MeshSpec
from ray_lightning_tpu.parallel.plan import dp_degree

__all__ = ["ElasticBudget"]


def _default_spec(world: int) -> MeshSpec:
    return MeshSpec(data=world)


@dataclasses.dataclass
class ElasticBudget:
    """The supervisor's world-size ladder. See module docstring."""

    min_world: int = 1
    #: None: the launch world size (the supervisor fills it in)
    max_world: Optional[int] = None
    #: candidate worlds must be multiples of this (e.g. hosts come in
    #: groups of 4 chips)
    divisible_by: int = 1
    #: the job's mesh template at a given world — legality is "this
    #: resolves" (MeshSpec.resolve raises on bad divisibility, exactly
    #: like the pre-flight plan checker)
    spec_for: Callable[[int], MeshSpec] = _default_spec
    #: global batch (rows/step) for the divisibility leg + batch_plan
    global_batch: Optional[int] = None
    #: how many topology changes (shrinks + grows) the run may perform
    max_reshards: int = 4
    #: legacy capacity hook: () -> currently available world size.
    #: Takes precedence over the oracle when set (back-compat).
    capacity_fn: Optional[Callable[[], int]] = None
    #: the capacity oracle (autoscale/capacity.py) — the SAME truth the
    #: serving autoscale controller consults: RLT_CAPACITY env
    #: override, probe file, optional WorkerGroup spawn probe. None =
    #: the process-wide default oracle (env + file). When NO source
    #: answers, the oracle falls back to the resolved max but LABELS
    #: it (source="assumed") — the supervisor records that label in
    #: the reshard ledger on a refused grow, so an assumption can
    #: never masquerade as a measurement (the retired silent
    #: assume-restored default).
    oracle: Optional[Any] = None

    def resolved_max(self, launch_world: int) -> int:
        return self.max_world if self.max_world is not None \
            else launch_world

    def legal(self, world: int, launch_world: Optional[int] = None) -> bool:
        """Is ``world`` a legal rung of the ladder?"""
        if world < max(1, self.min_world):
            return False
        if launch_world is not None and world > self.resolved_max(
                launch_world):
            return False
        if self.divisible_by > 1 and world % self.divisible_by:
            return False
        try:
            spec = self.spec_for(world).resolve(world)
        except (ValueError, ZeroDivisionError):
            return False
        if self.global_batch is not None:
            if self.global_batch % dp_degree(spec):
                return False
        return True

    def legal_worlds(self, launch_world: int) -> List[int]:
        """Every legal rung from min_world to the resolved max,
        ascending."""
        hi = self.resolved_max(launch_world)
        return [w for w in range(max(1, self.min_world), hi + 1)
                if self.legal(w, launch_world)]

    def largest_legal(self, available: int,
                      launch_world: int) -> Optional[int]:
        """The largest legal world size <= ``available`` (the survivor
        count / reported capacity); None when even min_world does not
        fit — the run has no rung left and must fail."""
        hi = min(available, self.resolved_max(launch_world))
        for w in range(hi, max(1, self.min_world) - 1, -1):
            if self.legal(w, launch_world):
                return w
        return None

    def capacity_answer(self, launch_world: int):
        """The capacity oracle's full answer (worlds + source +
        detail) — what the supervisor stamps into the reshard ledger
        when a grow is refused. Resolution: the legacy ``capacity_fn``
        when set, else the configured/shared `CapacityOracle`
        (env -> probe file -> optional spawn probe), else the resolved
        max LABELED ``source="assumed"``."""
        from ray_lightning_tpu.autoscale.capacity import (
            CapacityAnswer, default_oracle,
        )

        if self.capacity_fn is not None:
            try:
                return CapacityAnswer(max(0, int(self.capacity_fn())),
                                      "capacity_fn")
            except Exception as exc:  # noqa: BLE001 — a broken oracle
                # must not kill the supervisor; nothing came back
                return CapacityAnswer(
                    0, "capacity_fn",
                    f"oracle raised {type(exc).__name__}: "
                    f"{str(exc)[:200]}")
        oracle = self.oracle if self.oracle is not None \
            else default_oracle()
        return oracle.query(assume=self.resolved_max(launch_world))

    def capacity(self, launch_world: int) -> int:
        """Currently available world size per `capacity_answer`. The
        built-in chain always answers (the assume= fallback is the
        labeled resolved max); the None guard exists only for a
        user-supplied ``oracle`` whose query() ignores ``assume`` —
        such an oracle's silence reads as the historical
        assumed-restored value, never as zero."""
        worlds = self.capacity_answer(launch_world).worlds
        return worlds if worlds is not None \
            else self.resolved_max(launch_world)

    def batch_plan(self, old_world: int, new_world: int) -> Dict[str, Any]:
        """The honest batch story of a world change. When the global
        batch is known: the accumulation factor that would preserve it
        (whole factors only — `Trainer(accumulate_grad_batches=k)`) or
        the re-planned global batch otherwise, stated as such."""
        plan: Dict[str, Any] = {
            "old_world": int(old_world),
            "new_world": int(new_world),
        }
        try:
            old_dp = dp_degree(self.spec_for(old_world).resolve(old_world))
            new_dp = dp_degree(self.spec_for(new_world).resolve(new_world))
        except (ValueError, ZeroDivisionError):
            plan["note"] = "mesh template did not resolve; batch story unknown"
            return plan
        plan["old_dp"] = old_dp
        plan["new_dp"] = new_dp
        if old_dp == new_dp:
            plan["global_batch_preserved"] = True
            return plan
        if old_dp % new_dp == 0:
            k = old_dp // new_dp
            plan["grad_accum_to_preserve"] = k
            plan["global_batch_preserved"] = False
            plan["note"] = (
                f"dp degree {old_dp} -> {new_dp}: per-step global batch "
                f"scales by {new_dp}/{old_dp} unless the trainer runs "
                f"accumulate_grad_batches={k}")
        else:
            plan["global_batch_preserved"] = False
            plan["note"] = (
                f"dp degree {old_dp} -> {new_dp}: no whole accumulation "
                "factor preserves the global batch — it is re-planned "
                f"to {new_dp}/{old_dp} of the original")
        if self.global_batch is not None:
            plan["old_global_batch"] = int(self.global_batch)
            plan["replanned_global_batch"] = int(
                self.global_batch * new_dp / old_dp)
        return plan
