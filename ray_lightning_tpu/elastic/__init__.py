"""elastic/ — cross-topology checkpoint resharding, elastic supervision
budgets, and DCN-aware multi-slice planning (docs/ELASTIC.md).

Three legs sharing one topology-change vocabulary:

  reshard   `reshard_restore` — restore any provenance-stamped
            checkpoint onto any target sharding (mesh-to-mesh moves,
            world-size changes); the Trainer stamps provenance into
            every checkpoint and validates cross-mesh restores.
  budget    `ElasticBudget` — the supervisor's world-size ladder:
            legal survivor sizes (divisibility via the plan checker),
            shrink on lost capacity instead of dying, grow back when
            capacity returns, honest batch replanning.
  DCN       the second network tier lives in analysis/costmodel.py
            (`parse_topology("2xv5p-64")`) and tracecheck itemizes
            ICI vs DCN bytes per step; RLT306 flags shard axes that
            would cross slices.
"""
from ray_lightning_tpu.elastic.budget import ElasticBudget
from ray_lightning_tpu.elastic.reshard import (
    ReshardError,
    checkpoint_provenance,
    reshard_arrays,
    reshard_restore,
    validate_reshard,
)

__all__ = [
    "ElasticBudget", "ReshardError", "checkpoint_provenance",
    "reshard_arrays", "reshard_restore", "validate_reshard",
]
