"""``python -m ray_lightning_tpu elastic`` — the elastic-training
smoke gate (docs/ELASTIC.md), runnable on a box with no accelerator.

``--smoke`` (the format.sh gate) runs two CPU-SPMD legs:

  reshard   an 8-device fsdp=8 run saves a provenance-stamped
            checkpoint; it is restored onto a 4-device fsdp=4 mesh and
            every param/opt-state leaf must be BITWISE-equal to the
            source checkpoint; a fresh trainer then resumes training
            from it on the smaller mesh (the cross-topology restore is
            the trainer's own `_reshard_move` path, recorded as a
            `reshard` span).
  shrink    a 2-process supervised run with an injected worker kill
            and a retry policy that refuses any same-size relaunch
            (max_restarts=0) must consult its ElasticBudget, reshard
            the latest valid checkpoint onto the survivor world
            (2 -> 1), resume, and converge — with the world change in
            `SupervisedResult.reshards` and the `reshard_s` goodput
            bucket present in the report.
"""
from __future__ import annotations

import argparse
import json
import sys

# ---- smoke factories: module-level so cloudpickle ships them by
# reference and workers import this module ----

_SMOKE_CLASSES = 4
_SMOKE_ROWS = 256
_SMOKE_BATCH = 16


def _smoke_module():
    from ray_lightning_tpu.models.mlp import MLPClassifier

    return MLPClassifier(features=(32,), num_classes=_SMOKE_CLASSES,
                         lr=5e-2)


def _smoke_trainer():
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.parallel.strategy import FSDP

    return Trainer(
        # FSDP (not DP) on purpose: the world change then moves REAL
        # shards, not replicated copies — min_shard_size lowered so the
        # smoke MLP's small leaves actually shard
        strategy=FSDP(min_shard_size=8),
        max_epochs=2,
        enable_progress_bar=False,
        enable_checkpointing=False,  # the supervisor adds its own cadence
        seed=0,
        log_every_n_steps=1,
    )


def _smoke_data():
    import jax
    import numpy as np

    from ray_lightning_tpu import DataLoader

    rng = np.random.default_rng(0)
    centers = rng.normal(size=(_SMOKE_CLASSES, 8)) * 3
    y = rng.integers(0, _SMOKE_CLASSES, size=_SMOKE_ROWS)
    x = (centers[y] + rng.normal(size=(_SMOKE_ROWS, 8)) * 0.1).astype(
        np.float32)
    shard = dict(num_shards=jax.process_count(),
                 shard_index=jax.process_index())
    train = DataLoader({"x": x, "y": y}, batch_size=_SMOKE_BATCH,
                       shuffle=True, **shard)
    val = DataLoader({"x": x, "y": y}, batch_size=_SMOKE_BATCH, **shard)
    return train, val


def _reshard_leg_remote():
    """Runs as ONE worker with 8 virtual CPU devices: train on fsdp=8,
    checkpoint, reshard-restore onto fsdp=4 bitwise, then resume
    training on the 4-device mesh through the Trainer's own
    cross-topology restore path."""
    import os
    import tempfile

    import jax
    import numpy as np

    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.checkpoint.io import load_checkpoint, read_meta
    from ray_lightning_tpu.elastic.reshard import reshard_restore
    from ray_lightning_tpu.parallel.strategy import FSDP

    out: dict = {"ok": False}
    base = tempfile.mkdtemp(prefix="rlt_elastic_smoke_")
    ck = os.path.join(base, "ck")

    module = _smoke_module()
    trainer = _smoke_trainer()
    train, val = _smoke_data()
    trainer.fit(module, train, val)
    trainer.save_checkpoint(ck)
    src_world = len(jax.devices())
    out["src_world"] = src_world
    out["provenance"] = sorted(
        k for k in read_meta(ck) if k in ("mesh_spec", "topology",
                                          "param_specs"))

    # bitwise leg: restore onto a FRESH 4-device fsdp=4 mesh and
    # compare leaf-for-leaf against the source checkpoint's contents
    s4 = FSDP(num_workers=4, min_shard_size=8)
    s4.setup()
    src = load_checkpoint(ck)  # host gather of the written bytes
    import jax.numpy as jnp

    tgt_params = s4.shard_params(
        jax.tree.map(jnp.zeros_like, src["params"]))
    tgt_opt = jax.tree.map(
        jnp.zeros_like, src["opt_state"])
    tgt_opt = jax.device_put(
        tgt_opt, s4.opt_state_shardings(
            jax.eval_shape(lambda t: t, tgt_opt), tgt_params))
    target = {"params": tgt_params, "opt_state": tgt_opt,
              "step": jax.device_put(jnp.zeros((), jnp.int32),
                                     s4.replicated())}
    restored = reshard_restore(ck, target)
    mismatches = 0
    leaves = 0
    for a, b in zip(jax.tree.leaves(
            {"params": src["params"], "opt_state": src["opt_state"]}),
            jax.tree.leaves({"params": restored["params"],
                             "opt_state": restored["opt_state"]})):
        leaves += 1
        if not np.array_equal(np.asarray(a),
                              np.asarray(jax.device_get(b))):
            mismatches += 1
    out["leaves"] = leaves
    out["bitwise_equal"] = mismatches == 0
    out["restored_world"] = int(
        jax.tree.leaves(restored["params"])[0].sharding.mesh.size)

    # continue-training leg: a fresh trainer on the 4-device mesh
    # resumes FROM the 8-device checkpoint (the Trainer's _reshard_move
    # validates + spans the move) and still converges
    module2 = _smoke_module()
    trainer2 = Trainer(strategy=FSDP(num_workers=4, min_shard_size=8),
                       max_epochs=3, enable_progress_bar=False,
                       enable_checkpointing=False, seed=0,
                       log_every_n_steps=1)
    train2, val2 = _smoke_data()
    metrics = trainer2.fit(module2, train2, val2, ckpt_path=ck)
    acc = metrics.get("ptl/val_accuracy")
    out["continued_val_accuracy"] = (float(acc) if acc is not None
                                     else None)
    out["continued"] = acc is not None and float(acc) > 0.8
    out["ok"] = bool(out["bitwise_equal"] and out["continued"]
                     and len(out["provenance"]) == 3)
    return out


def add_elastic_parser(sub) -> None:
    p = sub.add_parser(
        "elastic",
        help="elastic-training smoke gate: cross-topology reshard "
             "restore (bitwise) + supervised shrink-on-preemption "
             "(docs/ELASTIC.md)")
    p.add_argument("--smoke", action="store_true",
                   help="run the format.sh gate: the 8->4 device "
                        "reshard-bitwise leg and the world 2->1 "
                        "supervised shrink leg, all on CPU")
    p.add_argument("--processes", type=int, default=2,
                   help="shrink leg's launch world size")
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--json", action="store_true", dest="as_json",
                   default=argparse.SUPPRESS)


def _shrink_leg(args, base_dir: str) -> dict:
    import os

    from ray_lightning_tpu.elastic.budget import ElasticBudget
    from ray_lightning_tpu.resilience.policy import RetryPolicy
    from ray_lightning_tpu.resilience.supervisor import (
        ResilienceConfig,
        SupervisedFailure,
        fit_supervised,
    )

    cfg = ResilienceConfig(
        checkpoint_dir=os.path.join(base_dir, "shrink"),
        # max_restarts=0: the same-size relaunch is REFUSED, so the
        # kill can only be survived by the elastic shrink — exactly
        # the "preemption budget exhausted" acceptance scenario
        policy=RetryPolicy(max_restarts=0, backoff_base_s=0.2,
                           jitter=0.0),
        save_every_n_steps=1,
        stall_timeout_s=0.0,
        heartbeat_interval_s=1.0,
        elastic=ElasticBudget(min_world=1, max_reshards=2),
        faults=f"kill:rank={min(1, args.processes - 1)},step=3",
    )
    leg: dict = {"ok": False}
    try:
        supervised = fit_supervised(
            _smoke_module, _smoke_trainer, _smoke_data, args.processes,
            resilience=cfg, platform="cpu",
            num_cpu_devices_per_process=1, return_weights=False,
            timeout=args.timeout)
    except SupervisedFailure as exc:
        leg["error"] = str(exc)
        return leg
    acc = supervised.result.metrics.get("ptl/val_accuracy")
    buckets = ((supervised.goodput or {}).get("buckets") or {})
    leg.update({
        "reshards": supervised.reshards,
        "final_world": supervised.final_world,
        "val_accuracy": float(acc) if acc is not None else None,
        "reshard_bucket_present": "reshard_s" in buckets,
        "reshard_s": buckets.get("reshard_s"),
    })
    shrunk = (len(supervised.reshards) >= 1
              and supervised.final_world == 1
              and supervised.reshards[0]["reason"] == "shrink")
    converged = acc is not None and float(acc) > 0.8
    leg["ok"] = bool(shrunk and converged
                     and leg["reshard_bucket_present"])
    if not leg["ok"]:
        leg["error"] = (
            f"shrink leg failed: reshards={supervised.reshards}, "
            f"final_world={supervised.final_world}, acc={acc}, "
            f"reshard_bucket={leg['reshard_bucket_present']}")
    return leg


def run_elastic(args) -> int:
    import tempfile

    if not args.smoke:
        print("error: only --smoke is implemented; see docs/ELASTIC.md "
              "for the library API (elastic.reshard_restore, "
              "ResilienceConfig(elastic=ElasticBudget(...)))",
              file=sys.stderr)
        return 2
    from ray_lightning_tpu.runtime.launch import launch

    out: dict = {}
    base = args.checkpoint_dir or tempfile.mkdtemp(
        prefix="rlt_elastic_smoke_")
    out["checkpoint_dir"] = base

    # leg 1: reshard-bitwise, one worker process with 8 CPU devices
    try:
        results = launch(_reshard_leg_remote, 1, platform="cpu",
                         num_cpu_devices_per_process=8,
                         timeout=args.timeout)
        out["reshard"] = results[0]
    except Exception as exc:  # noqa: BLE001 — the gate must report,
        # not traceback
        out["reshard"] = {"ok": False,
                          "error": f"{type(exc).__name__}: {exc}"}

    # leg 2: supervised shrink 2 -> 1
    out["shrink"] = _shrink_leg(args, base)

    out["ok"] = bool(out["reshard"].get("ok") and out["shrink"].get("ok"))
    if getattr(args, "as_json", False):
        print(json.dumps(out))
    else:
        r, s = out["reshard"], out["shrink"]
        print(f"elastic {'ok' if out['ok'] else 'FAILED'}:")
        print(f"  reshard: {'ok' if r.get('ok') else 'FAILED'} "
              f"bitwise_equal={r.get('bitwise_equal')} "
              f"leaves={r.get('leaves')} "
              f"continued_acc={r.get('continued_val_accuracy')}")
        print(f"  shrink:  {'ok' if s.get('ok') else 'FAILED'} "
              f"reshards={[(e['from_world'], e.get('to_world')) for e in s.get('reshards') or []]} "
              f"acc={s.get('val_accuracy')} "
              f"reshard_bucket={s.get('reshard_bucket_present')}")
        for leg in ("reshard", "shrink"):
            if out[leg].get("error"):
                print(f"  {leg} error: {out[leg]['error']}",
                      file=sys.stderr)
    return 0 if out["ok"] else 1
