"""Resharding restore: load any checkpoint onto any target sharding.

Every checkpoint used to be pinned to the mesh that wrote it — the
restore path rebuilt the writer's exact layout, so a k-host preemption
(or a deliberate re-plan) orphaned the run's whole history. This module
breaks the pin (docs/ELASTIC.md "resharding restore"):

  * checkpoints carry **topology provenance** (checkpoint/io.py
    `sharding_provenance`, stamped by the Trainer into every meta.json):
    the writing mesh's axis sizes, device/process counts, and each param
    leaf's PartitionSpec;
  * `reshard_restore(path, target)` restores the checkpoint onto the
    TARGET tree's shardings — an arbitrary mesh-to-mesh move (fsdp=8 ->
    fsdp=4, a dp<->fsdp swap, a world-size change), validated against
    the provenance first so an illegal or accidental move fails with
    the axis named instead of a silent mislayout. Opt-state and any
    extra slots (trainguard EMA state) ride the same move: the target
    tree's layout is the contract, leaf for leaf.

The move itself generalizes the `match_partition_rules` pattern (rules
-> specs -> per-leaf placement) to arbitrary mesh-to-mesh transitions:
the target specs come from the target strategy's own composition logic
(the same code a fresh run would use), and the storage layer (orbax
holds GLOBAL arrays; each host reads the shards its target layout
needs) performs the actual movement — no gather-to-host round-trip, so
an 8B-param resume onto a survivor mesh streams only what each host
keeps.

Back-compat: a checkpoint WITHOUT provenance (written before this
subsystem) has an unknowable writing mesh, so no cross-mesh move can
be validated against it: `reshard_restore` (and the supervisor's
elastic resize) refuse it with a ReshardError naming the gap, and the
legacy path (`checkpoint.restore_checkpoint`) restores it with no
cross-mesh validation — the Trainer logs that blind spot.
"""
from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)

__all__ = ["ReshardError", "checkpoint_provenance", "validate_reshard",
           "reshard_restore", "reshard_arrays"]


class ReshardError(RuntimeError):
    """A cross-topology restore that cannot (or must not) proceed:
    missing provenance, contradictory provenance, or a malformed
    target mesh."""


def checkpoint_provenance(path: str) -> Dict[str, Any]:
    """The topology-provenance stamps of a checkpoint's meta
    (``mesh_spec`` / ``topology`` / ``param_specs``); empty dict for a
    legacy checkpoint that carries none."""
    from ray_lightning_tpu.checkpoint.io import read_meta

    meta = read_meta(path)
    return {k: meta[k] for k in ("mesh_spec", "topology", "param_specs")
            if k in meta}


def _live(sizes: Mapping[str, Any]) -> Dict[str, int]:
    return {str(k): int(v) for k, v in sizes.items() if int(v) > 1}


def validate_reshard(meta: Mapping[str, Any],
                     target_mesh: Mapping[str, int]) -> Dict[str, Any]:
    """Validate a move from the checkpoint described by ``meta`` onto a
    mesh with ``target_mesh`` axis sizes. Returns the move summary

        {"from_mesh", "to_mesh", "from_world", "to_world",
         "changed_axes", "world_change"}

    Raises ReshardError when the checkpoint has no provenance (legacy:
    identical-sharding restore only), when its provenance is
    self-contradictory, or when the target mesh is malformed. The
    SHAPE-level agreement (every leaf's global shape unchanged) is
    enforced by the storage layer during the actual restore — global
    shapes are mesh-independent, so a mesh-level-legal move can only
    fail there if the model itself changed."""
    mesh_spec = meta.get("mesh_spec")
    if not mesh_spec:
        raise ReshardError(
            "checkpoint carries no sharding provenance (no mesh_spec in "
            "meta.json — written before elastic/ existed?): a move from "
            "an unknowable writing mesh cannot be validated. Restore it "
            "legacy-style via checkpoint.restore_checkpoint (no "
            "cross-mesh validation), or re-save it once on the current "
            "mesh to stamp provenance, then reshard")
    src = _live(mesh_spec)
    try:
        dst = _live(target_mesh)
    except (TypeError, ValueError) as exc:
        raise ReshardError(
            f"malformed target mesh {target_mesh!r}: {exc}") from exc
    if any(int(v) < 1 for v in dict(target_mesh).values()):
        raise ReshardError(
            f"malformed target mesh {target_mesh!r}: axis sizes must "
            "be >= 1")
    # provenance self-consistency (the same checks verify_checkpoint
    # runs): a contradictory stamp would make this validation fiction
    from ray_lightning_tpu.checkpoint.io import _verify_provenance

    ok, reason = _verify_provenance(dict(meta))
    if not ok:
        raise ReshardError(f"checkpoint provenance is invalid: {reason}")
    from_world = 1
    for v in src.values():
        from_world *= v
    to_world = 1
    for v in dst.values():
        to_world *= v
    changed = sorted(set(src) ^ set(dst)
                     | {ax for ax in set(src) & set(dst)
                        if src[ax] != dst[ax]})
    return {
        "from_mesh": src,
        "to_mesh": dst,
        "from_world": from_world,
        "to_world": to_world,
        "changed_axes": changed,
        "world_change": to_world != from_world,
    }


def _target_mesh_sizes(target: Any) -> Optional[Dict[str, int]]:
    """Axis sizes of the first mesh found on the target tree's
    shardings (None when the tree carries no NamedSharding — e.g. a
    host-numpy tree, which is load_checkpoint territory)."""
    import jax

    for leaf in jax.tree.leaves(target):
        sharding = getattr(leaf, "sharding", None)
        mesh = getattr(sharding, "mesh", None)
        shape = getattr(mesh, "shape", None)
        if shape:
            return {str(k): int(v) for k, v in dict(shape).items()}
    return None


def reshard_restore(path: str, target: Any, *,
                    verify: bool = True) -> Any:
    """Restore the checkpoint at ``path`` onto ``target``'s shardings —
    an arbitrary mesh-to-mesh move. ``target`` is a pytree of jax.Arrays
    or ShapeDtypeStructs whose ``.sharding`` gives the layout to restore
    into (the same contract as `checkpoint.restore_checkpoint`); every
    leaf present in the target — params, opt-state, guard/EMA slots —
    reshards to its target layout.

    The move is validated against the checkpoint's provenance first
    (`validate_reshard`); ``verify=True`` additionally runs the
    completeness/digest check so a torn or corrupt checkpoint is never
    the source of a topology change. Returns the restored tree (runtime-
    owned buffers — safe to donate, like restore_checkpoint)."""
    import os

    from ray_lightning_tpu.checkpoint.io import (
        read_meta,
        restore_checkpoint,
        verify_checkpoint,
    )

    path = os.path.abspath(path)
    if verify:
        ok, reason = verify_checkpoint(path)
        if not ok:
            raise ReshardError(
                f"refusing to reshard from invalid checkpoint {path}: "
                f"{reason}")
    sizes = _target_mesh_sizes(target)
    if sizes is None:
        raise ReshardError(
            "target tree carries no NamedSharding — reshard_restore "
            "needs the target layout (build the tree under the target "
            "strategy, or use checkpoint.load_checkpoint for a host "
            "gather)")
    move = validate_reshard(read_meta(path), sizes)
    log.info("resharding %s: %s -> %s (world %d -> %d, axes %s)",
             path, move["from_mesh"], move["to_mesh"],
             move["from_world"], move["to_world"],
             ",".join(move["changed_axes"]) or "unchanged")
    return restore_checkpoint(path, target)


def reshard_arrays(tree: Any, shardings: Any) -> Any:
    """In-memory mesh-to-mesh move: place an already-loaded tree onto
    new shardings (same-process convenience; the checkpoint path is
    `reshard_restore`). Works across meshes — XLA reshards through
    host/ICI as needed."""
    import jax

    return jax.device_put(tree, shardings)
