"""MNIST end-to-end with the DataParallel strategy — plain train + sweep.

Parity target: reference examples/ray_ddp_example.py:1-168 (MNIST training
under RayPlugin, optional Tune sweep, --smoke-test CI mode). TPU-first
differences: the "workers" are mesh devices (XLA SPMD data parallelism),
not Ray actors; the sweep reserves integral device groups instead of
extra_cpu oversubscription (reference :107-112).

Run:
    python examples/mnist_dp_example.py --smoke-test
    python examples/mnist_dp_example.py --num-workers 8 --max-epochs 5
    python examples/mnist_dp_example.py --tune --num-samples 4
"""
from __future__ import annotations

import argparse
import os
import tempfile

import numpy as np
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_mnist(smoke: bool = False):
    """Real MNIST via torchvision when available; a separable synthetic
    stand-in otherwise (the sandbox has no downloads — the reference's
    examples used an init_hook + FileLock for the same per-node download
    problem, reference ray_ddp_tune.py:22-25,40)."""
    try:
        from torchvision.datasets import MNIST  # noqa: PLC0415

        root = os.path.join(tempfile.gettempdir(), "mnist")
        train = MNIST(root, train=True, download=True)
        x = (train.data.numpy().astype(np.float32) / 255.0).reshape(-1, 784)
        y = train.targets.numpy().astype(np.int32)
    except Exception:
        rng = np.random.default_rng(0)
        n = 2048 if smoke else 16384
        y = rng.integers(0, 10, size=n).astype(np.int32)
        centers = rng.standard_normal((10, 784)).astype(np.float32) * 2.0
        x = centers[y] + rng.standard_normal((n, 784)).astype(np.float32)
    if smoke:
        x, y = x[:2048], y[:2048]
    split = int(0.9 * len(x))
    return ({"x": x[:split], "y": y[:split]},
            {"x": x[split:], "y": y[split:]})


def make_module(config):
    import flax.linen as nn
    import optax

    from ray_lightning_tpu import TpuModule

    class _MLP(nn.Module):
        hidden1: int
        hidden2: int

        @nn.compact
        def __call__(self, x):
            x = nn.relu(nn.Dense(self.hidden1)(x))
            x = nn.relu(nn.Dense(self.hidden2)(x))
            return nn.Dense(10)(x)

    class MNISTClassifier(TpuModule):
        def __init__(self, lr, hidden1, hidden2):
            super().__init__()
            self.save_hyperparameters(lr=lr, hidden1=hidden1, hidden2=hidden2)
            self.lr, self.h1, self.h2 = lr, hidden1, hidden2

        def configure_model(self):
            return _MLP(self.h1, self.h2)

        def configure_optimizers(self):
            return optax.adam(self.lr)

        def training_step(self, params, batch, rng):
            logits = self.apply(params, batch["x"])
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"]).mean()
            self.log("ptl/train_loss", loss)
            return loss

        def validation_step(self, params, batch):
            logits = self.apply(params, batch["x"])
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"]).mean()
            acc = (logits.argmax(-1) == batch["y"]).mean()
            return {"ptl/val_loss": loss, "ptl/val_accuracy": acc}

    return MNISTClassifier(config["lr"], config["hidden1"], config["hidden2"])


def train_mnist(config, num_workers, max_epochs, smoke, callbacks=None,
                root_dir=None):
    from ray_lightning_tpu import DataLoader, DataParallel, Trainer

    train, val = load_mnist(smoke)
    module = make_module(config)
    trainer = Trainer(
        strategy=DataParallel(num_workers=num_workers),
        max_epochs=max_epochs,
        limit_train_batches=8 if smoke else None,
        callbacks=callbacks,
        default_root_dir=root_dir or os.path.join(os.getcwd(), "mnist_dp"),
        enable_progress_bar=False,
        log_every_n_steps=10,
    )
    trainer.fit(
        module,
        DataLoader(train, batch_size=config["batch_size"], shuffle=True,
                   drop_last=True),
        DataLoader(val, batch_size=config["batch_size"], drop_last=True),
    )
    acc = trainer.callback_metrics.get("ptl/val_accuracy")
    print(f"final val accuracy: {float(acc):.4f}")
    return trainer


def tune_mnist(num_workers, num_samples, max_epochs, smoke):
    """Sweep analog of the reference's tune_mnist
    (reference examples/ray_ddp_example.py:79-116)."""
    from ray_lightning_tpu import sweep

    def trainable(config):
        train_mnist(
            config, num_workers, max_epochs, smoke,
            callbacks=[sweep.TuneReportCallback(
                metrics={"loss": "ptl/val_loss", "acc": "ptl/val_accuracy"})],
            root_dir=sweep.get_trial_dir(),
        )

    analysis = sweep.run(
        trainable,
        config={
            "lr": sweep.loguniform(1e-4, 1e-1),
            "hidden1": sweep.choice([64, 128]),
            "hidden2": sweep.choice([128, 256]),
            "batch_size": sweep.choice([64, 128]),
        },
        num_samples=num_samples,
        metric="loss",
        mode="min",
        executor="inline" if smoke else "process",
        resources_per_trial=sweep.TpuResources(chips=num_workers),
        name="tune_mnist",
    )
    print("Best hyperparameters:", analysis.best_config)
    return analysis


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--num-workers", type=int, default=None,
                   help="devices in the data-parallel mesh (default: all)")
    p.add_argument("--max-epochs", type=int, default=3)
    p.add_argument("--tune", action="store_true", help="run the HPO sweep")
    p.add_argument("--num-samples", type=int, default=4)
    p.add_argument("--smoke-test", action="store_true")
    args = p.parse_args()

    if args.smoke_test:
        # CI mode (reference :152-158): tiny run on a virtual CPU mesh.
        from ray_lightning_tpu.utils import simulate_cpu_devices

        simulate_cpu_devices(2)
        args.num_workers = args.num_workers or 2
        args.max_epochs = 1

    if args.tune:
        tune_mnist(args.num_workers or 1, args.num_samples,
                   args.max_epochs, args.smoke_test)
    else:
        config = {"lr": 1e-3, "hidden1": 128, "hidden2": 256,
                  "batch_size": 128}
        train_mnist(config, args.num_workers, args.max_epochs,
                    args.smoke_test)


if __name__ == "__main__":
    main()
