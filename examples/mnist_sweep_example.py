"""HPO sweep with checkpointing + ASHA early termination.

Parity target: reference examples/ray_ddp_tune.py:1-127 (Tune sweep over
RayPlugin trials with TuneReportCheckpointCallback). TPU-first
differences: trials reserve integral device groups (SURVEY §7.4 #4), the
scheduler's stop verdict unwinds the trial cooperatively, and checkpoints
are written in place with only paths reported (SURVEY §2.4).

Run:
    python examples/mnist_sweep_example.py --smoke-test
    python examples/mnist_sweep_example.py --num-samples 8 --chips-per-trial 4
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mnist_dp_example import load_mnist, make_module


def tune_mnist_asha(num_samples, chips_per_trial, max_epochs, smoke):
    from ray_lightning_tpu import DataLoader, DataParallel, Trainer, sweep

    train, val = load_mnist(smoke)

    def trainable(config):
        module = make_module(config)
        trainer = Trainer(
            strategy=DataParallel(num_workers=chips_per_trial),
            max_epochs=max_epochs,
            limit_train_batches=8 if smoke else None,
            callbacks=[sweep.TuneReportCheckpointCallback(
                metrics={"loss": "ptl/val_loss", "acc": "ptl/val_accuracy"})],
            default_root_dir=sweep.get_trial_dir(),
            enable_checkpointing=False,
            enable_progress_bar=False,
        )
        trainer.fit(
            module,
            DataLoader(train, batch_size=config["batch_size"], shuffle=True,
                       drop_last=True),
            DataLoader(val, batch_size=config["batch_size"], drop_last=True),
        )

    analysis = sweep.run(
        trainable,
        config={
            "lr": sweep.loguniform(1e-4, 1e-1),
            "hidden1": sweep.choice([64, 128]),
            "hidden2": sweep.choice([128, 256]),
            "batch_size": sweep.choice([64, 128]),
        },
        num_samples=num_samples,
        metric="loss",
        mode="min",
        scheduler=sweep.ASHAScheduler(max_t=max_epochs, grace_period=1,
                                      reduction_factor=2),
        executor="inline" if smoke else "process",
        resources_per_trial=sweep.TpuResources(chips=chips_per_trial),
        name="tune_mnist_asha",
    )
    print("Best hyperparameters:", analysis.best_config)
    print("Best checkpoint:", analysis.best_checkpoint)
    for row in analysis.dataframe():
        print(row)
    return analysis


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--num-samples", type=int, default=4)
    p.add_argument("--chips-per-trial", type=int, default=1)
    p.add_argument("--max-epochs", type=int, default=4)
    p.add_argument("--smoke-test", action="store_true")
    args = p.parse_args()

    if args.smoke_test:
        from ray_lightning_tpu.utils import simulate_cpu_devices

        simulate_cpu_devices(2)
        args.num_samples = 2
        args.max_epochs = 2

    tune_mnist_asha(args.num_samples, args.chips_per_trial,
                    args.max_epochs, args.smoke_test)


if __name__ == "__main__":
    main()
