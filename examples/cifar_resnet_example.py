"""ResNet on CIFAR-10 with data-parallel sharding (BASELINE config 2:
"ResNet-50 / CIFAR-10, 8-worker data-parallel").

Real CIFAR-10 via torchvision when available, a separable synthetic
stand-in otherwise (no downloads in CI).

Run:
    python examples/cifar_resnet_example.py --smoke-test
    python examples/cifar_resnet_example.py --variant resnet50 --num-workers 8
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_cifar(smoke: bool = False):
    try:
        from torchvision.datasets import CIFAR10  # noqa: PLC0415

        root = os.path.join(tempfile.gettempdir(), "cifar10")
        train = CIFAR10(root, train=True, download=True)
        x = train.data.astype(np.float32) / 255.0          # [N,32,32,3] NHWC
        y = np.asarray(train.targets, dtype=np.int32)
        x = (x - x.mean(axis=(0, 1, 2))) / x.std(axis=(0, 1, 2))
    except Exception:
        rng = np.random.default_rng(0)
        n = 512 if smoke else 8192
        y = rng.integers(0, 10, n).astype(np.int32)
        base = rng.standard_normal((10, 1, 1, 3)).astype(np.float32) * 3
        x = base[y] + 0.3 * rng.standard_normal(
            (n, 32, 32, 3)).astype(np.float32)
    if smoke:
        x, y = x[:512], y[:512]
    split = int(0.9 * len(x))
    return ({"x": x[:split], "y": y[:split]},
            {"x": x[split:], "y": y[split:]})


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--variant", default="resnet18",
                   choices=["resnet18", "resnet34", "resnet50"])
    p.add_argument("--num-workers", type=int, default=None)
    p.add_argument("--max-epochs", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--prefetch", action="store_true",
                   help="use the native C++ batch prefetcher")
    p.add_argument("--smoke-test", action="store_true")
    args = p.parse_args()

    if args.smoke_test:
        from ray_lightning_tpu.utils import simulate_cpu_devices

        simulate_cpu_devices(2)
        args.max_epochs, args.batch_size, args.lr = 2, 64, 0.05

    from ray_lightning_tpu import (
        DataLoader,
        DataParallel,
        Trainer,
        ThroughputMonitor,
    )
    from ray_lightning_tpu.models import ResNetModule

    train, val = load_cifar(args.smoke_test)
    steps = args.max_epochs * (len(train["y"]) // args.batch_size)
    module = ResNetModule(variant=args.variant, num_classes=10,
                          lr=args.lr, total_steps=max(steps, 2))
    trainer = Trainer(
        strategy=DataParallel(num_workers=args.num_workers),
        max_epochs=args.max_epochs,
        callbacks=[ThroughputMonitor()],
        default_root_dir=os.path.join(os.getcwd(), "cifar_resnet"),
        enable_progress_bar=False,
        log_every_n_steps=10,
    )
    trainer.fit(
        module,
        DataLoader(train, batch_size=args.batch_size, shuffle=True,
                   drop_last=True, prefetch=args.prefetch),
        DataLoader(val, batch_size=min(args.batch_size, len(val["y"])),
                   drop_last=True),
    )
    m = trainer.callback_metrics
    print(f"val_acc={float(m['val_acc']):.4f} "
          f"examples/sec={float(m.get('examples_per_sec', 0)):,.0f}")


if __name__ == "__main__":
    main()
