"""Cross-host (pod) launch: one driver script places one worker per host
VM over a pluggable transport and runs ONE SPMD fit across all of them.

Parity target: the reference's signature capability — workers placed on
arbitrary cluster nodes by the Ray scheduler with env bootstrap + rank
resolution (reference ray_ddp.py:106-164). Here placement is a transport
(runtime/transport.py): `SSHTransport` on a real pod, `LoopbackTransport`
to exercise the identical bootstrap/rendezvous path on one machine.

Run on a real v5p pod (driver on any VM with ssh to the hosts):
    python examples/pod_launch_example.py \
        --hosts 10.164.0.2 10.164.0.3 ... --remote-python python3

Locally / CI (full remote code path, fake hosts, CPU devices):
    python examples/pod_launch_example.py --smoke-test
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_module():
    from ray_lightning_tpu.models.mlp import MLPClassifier

    return MLPClassifier(features=(64,), num_classes=4, lr=5e-2)


def make_trainer():
    from ray_lightning_tpu import DataParallel, Trainer

    return Trainer(
        strategy=DataParallel(),
        max_epochs=2,
        enable_progress_bar=False,
        enable_checkpointing=False,
        seed=0,
    )


def make_data():
    from ray_lightning_tpu import DataLoader

    rng = np.random.default_rng(0)
    centers = rng.normal(size=(4, 16)) * 3
    y = rng.integers(0, 4, size=512)
    x = (centers[y] + rng.normal(size=(512, 16)) * 0.1).astype(np.float32)
    # No shard arguments: the distributed launcher FORCES per-host
    # sharding onto every loader (the reference's injected
    # DistributedSampler, ray_ddp.py:293-303) — each host yields its own
    # rows of the global batch; passing matching num_shards/shard_index
    # manually is accepted, disagreeing ones are a hard error.
    train = DataLoader({"x": x, "y": y}, batch_size=32, shuffle=True)
    val = DataLoader({"x": x, "y": y}, batch_size=32)
    return train, val


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--hosts", nargs="+", default=None,
                        help="host VM addresses, one worker per host")
    parser.add_argument("--remote-python", default="python3")
    parser.add_argument("--smoke-test", action="store_true",
                        help="fake 2-host run on local CPU devices")
    args = parser.parse_args()

    from ray_lightning_tpu.runtime import (
        LoopbackTransport,
        SSHTransport,
        fit_distributed,
    )

    if args.smoke_test:
        hosts = ["fake-host-a", "fake-host-b"]
        transport = LoopbackTransport()
        extra = dict(platform="cpu", num_cpu_devices_per_process=2,
                     env={"JAX_PLATFORMS": "cpu"})
    else:
        if not args.hosts:
            parser.error("--hosts is required without --smoke-test")
        hosts = args.hosts
        transport = SSHTransport(remote_python=args.remote_python)
        extra = {}

    result = fit_distributed(
        make_module, make_trainer, make_data,
        num_processes=len(hosts),
        hosts=hosts,
        transport=transport,
        timeout=600,
        **extra,
    )
    acc = result.metrics.get("ptl/val_accuracy")
    print(f"workers={len(hosts)} hosts={hosts}")
    print(f"final metrics: {result.metrics}")
    assert acc is not None and acc > 0.9, acc
    print("pod launch round-trip OK")


if __name__ == "__main__":
    main()
