"""Llama-3-style pretraining with FSDP sharding over a device mesh.

This stands where the reference's second-protocol example stood
(reference examples/ray_horovod_example.py:1-198): the alternative
distribution strategy demonstrated end-to-end. On TPU the "protocol"
choice (DDP vs Horovod) becomes a sharding-policy choice (DataParallel vs
FSDP/ShardedMesh over the same XLA collectives — SURVEY §2.2 Horovod row),
and the model is the BASELINE.json north-star config (Llama-8B FSDP).

Run:
    python examples/llama_fsdp_example.py --smoke-test
    python examples/llama_fsdp_example.py --model 8b --fsdp 64   # v5p-64
    python examples/llama_fsdp_example.py --model 1b --fsdp 4 --data 2
"""
from __future__ import annotations

import argparse
import os

import numpy as np
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def synthetic_tokens(vocab_size: int, n_seqs: int, seq_len: int, seed=0):
    """Synthetic corpus (the sandbox downloads nothing); swap in a real
    tokenized dataset loader in production."""
    rng = np.random.default_rng(seed)
    return {"tokens": rng.integers(
        0, vocab_size, (n_seqs, seq_len + 1)).astype(np.int32)}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", choices=["tiny", "1b", "8b"], default="1b")
    p.add_argument("--data", type=int, default=1, help="data-parallel degree")
    p.add_argument("--fsdp", type=int, default=0,
                   help="fsdp degree (default: all remaining devices)")
    p.add_argument("--tensor", type=int, default=1, help="tensor-parallel degree")
    p.add_argument("--pipe", type=int, default=1,
                   help="pipeline stages (GPipe over the pipe mesh axis)")
    p.add_argument("--microbatches", type=int, default=4,
                   help="pipeline microbatches per step (with --pipe > 1)")
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--max-steps", type=int, default=50)
    p.add_argument("--lr", type=float, default=3e-4)
    # perf levers (see README "Performance"; v5e sweep: remat off +
    # unrolled layers is fastest when activations fit)
    p.add_argument("--no-remat", action="store_true",
                   help="disable rematerialization (more HBM, no "
                        "backward recompute)")
    p.add_argument("--remat-policy",
                   choices=["nothing", "dots", "attn_out"],
                   default="nothing",
                   help="what the per-layer checkpoint saves: nothing / "
                        "all matmul outputs / the attention residuals")
    p.add_argument("--no-scan-layers", action="store_true",
                   help="unroll the layer stack (free schedule; pair "
                        "with --no-remat)")
    p.add_argument("--fused-ce", choices=["auto", "on", "off"],
                   default="auto",
                   help="chunked lm_head+CE; auto = on for vocab >= 64k")
    p.add_argument("--ce-inline-bwd", action="store_true",
                   help="compute CE grads inline in the forward scan "
                        "(no logits-tile recompute; +D x V residual)")
    p.add_argument("--mu-bf16", action="store_true",
                   help="store Adam's first moment in bf16 (-25%% "
                        "optimizer HBM; buys batch on capped chips)")
    p.add_argument("--smoke-test", action="store_true")
    args = p.parse_args()

    if args.smoke_test:
        from ray_lightning_tpu.utils import simulate_cpu_devices

        simulate_cpu_devices(4)

    import jax

    from ray_lightning_tpu import (
        DataLoader,
        ShardedMesh,
        ThroughputMonitor,
        Trainer,
    )
    from ray_lightning_tpu.models.llama import LlamaConfig, LlamaModule

    n_dev = len(jax.devices())
    on_tpu = jax.devices()[0].platform == "tpu"

    if args.smoke_test:
        cfg = LlamaConfig.tiny(use_flash=on_tpu)
        args.seq_len = min(args.seq_len, 128)
        args.batch_size = 4
        args.max_steps = 4
    elif args.model == "tiny":
        cfg = LlamaConfig.tiny(use_flash=on_tpu)
    elif args.model == "1b":
        cfg = LlamaConfig(vocab_size=32768, dim=2048, n_layers=16,
                          n_heads=16, n_kv_heads=8, hidden_dim=5632,
                          max_seq_len=args.seq_len, use_flash=on_tpu)
    else:
        cfg = LlamaConfig.llama3_8b(use_flash=on_tpu,
                                    max_seq_len=args.seq_len)

    import dataclasses

    if args.no_scan_layers and args.pipe > 1:
        p.error("--no-scan-layers conflicts with --pipe > 1 (the pipeline "
                "stage-splits the scanned layer stack)")
    cfg = dataclasses.replace(
        cfg,
        remat=not args.no_remat,
        remat_policy=args.remat_policy,
        scan_layers=not args.no_scan_layers,
        fused_ce={"auto": None, "on": True, "off": False}[args.fused_ce],
        ce_inline_bwd=args.ce_inline_bwd,
        pipeline_microbatches=args.microbatches if args.pipe > 1 else 0,
    )

    fsdp = args.fsdp or max(1, n_dev // (args.data * args.tensor * args.pipe))
    strategy = ShardedMesh(data=args.data, fsdp=fsdp, tensor=args.tensor,
                           pipe=args.pipe)

    seq_len = min(args.seq_len, cfg.max_seq_len)
    import jax.numpy as jnp

    module = LlamaModule(cfg, lr=args.lr,
                         warmup_steps=min(10, max(1, args.max_steps // 2)),
                         total_steps=args.max_steps,
                         mu_dtype=jnp.bfloat16 if args.mu_bf16 else None)
    data = synthetic_tokens(
        cfg.vocab_size,
        n_seqs=max(64, 4 * args.batch_size),
        seq_len=seq_len,
    )
    trainer = Trainer(
        strategy=strategy,
        max_epochs=10_000,           # bounded by max_steps
        max_steps=args.max_steps,
        callbacks=[ThroughputMonitor()],
        precision="bf16" if on_tpu else "f32",
        enable_checkpointing=not args.smoke_test,
        enable_progress_bar=True,
        log_every_n_steps=5,
        default_root_dir=os.path.join(os.getcwd(), "llama_fsdp"),
    )
    trainer.fit(module, DataLoader(data, batch_size=args.batch_size,
                                   shuffle=True, drop_last=True))

    m = trainer.callback_metrics
    tok_s = args.batch_size * seq_len / m["step_time_s"]
    print(f"mesh={dict(strategy.mesh.shape)} "
          f"loss={float(m['loss']):.4f} "
          f"step_time={float(m['step_time_s'])*1e3:.1f}ms "
          f"tokens/sec={tok_s:,.0f}")


if __name__ == "__main__":
    main()
