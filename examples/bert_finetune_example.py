"""BERT sequence-classification fine-tune (BASELINE config 3:
"BERT-base fine-tune, multi-host DP").

Uses a real tokenizer + weights when `transformers` assets are cached
locally; otherwise trains a from-scratch tiny BERT on synthetic
separable text (no downloads in CI).

Run:
    python examples/bert_finetune_example.py --smoke-test
    python examples/bert_finetune_example.py --num-workers 8 --max-epochs 3
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def synthetic_sst(n: int, seq: int, vocab: int, seed: int = 0):
    """Sentiment-shaped synthetic set: a handful of 'polarity tokens'
    whose balance decides the label — linearly separable but requires
    attention over the whole sequence."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, n).astype(np.int32)
    ids = rng.integers(10, vocab, (n, seq)).astype(np.int32)
    pos_tok, neg_tok = 3, 4
    for i in range(n):
        k = rng.integers(2, 6)
        slots = rng.choice(seq - 1, size=k, replace=False) + 1
        ids[i, slots] = pos_tok if y[i] else neg_tok
    ids[:, 0] = 1  # [CLS]
    return {"input_ids": ids,
            "attention_mask": np.ones((n, seq), np.int32),
            "labels": y}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--num-workers", type=int, default=None)
    p.add_argument("--max-epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--smoke-test", action="store_true")
    args = p.parse_args()

    if args.smoke_test:
        from ray_lightning_tpu.utils import simulate_cpu_devices

        simulate_cpu_devices(2)
        args.max_epochs, args.batch_size, args.seq_len = 3, 32, 32

    from ray_lightning_tpu import DataLoader, DataParallel, Trainer
    from ray_lightning_tpu.models import BertClassifierModule, BertConfig

    cfg = (BertConfig.tiny(use_flash=False, dropout=0.0)
           if args.smoke_test else
           BertConfig.base(max_seq_len=args.seq_len))
    n = 512 if args.smoke_test else 8192
    data = synthetic_sst(n, args.seq_len, cfg.vocab_size)
    split = int(0.9 * n)
    train = {k: v[:split] for k, v in data.items()}
    val = {k: v[split:] for k, v in data.items()}

    steps = args.max_epochs * (split // args.batch_size)
    module = BertClassifierModule(
        cfg, num_classes=2, lr=args.lr,
        warmup_steps=max(1, steps // 20), total_steps=max(steps, 2),
    )
    trainer = Trainer(
        strategy=DataParallel(num_workers=args.num_workers),
        max_epochs=args.max_epochs,
        default_root_dir=os.path.join(os.getcwd(), "bert_finetune"),
        enable_progress_bar=False,
        log_every_n_steps=10,
    )
    trainer.fit(
        module,
        DataLoader(train, batch_size=args.batch_size, shuffle=True,
                   drop_last=True),
        DataLoader(val, batch_size=args.batch_size, drop_last=True),
    )
    print(f"val_acc={float(trainer.callback_metrics['val_acc']):.4f}")


if __name__ == "__main__":
    main()
